"""Codec tests: native & NumPy backends, cross-decodability, error bounds.

Capability parity target: the reference's ZFP+LZ4 payload stack
(reference src/dispatcher.py:81-84) — here symmetric and first-party.
"""

import numpy as np
import pytest

from defer_tpu.codec import (BlockFloatCodec, LosslessCodec, PipelineCodec,
                             RawCodec, native_available)

RNG = np.random.RandomState(42)


def test_native_library_builds():
    assert native_available(), "g++ toolchain present; native codec must load"


@pytest.mark.parametrize("force_numpy", [False, True])
def test_blockfloat_roundtrip_error_bound(force_numpy):
    c = BlockFloatCodec(bits=8, force_numpy=force_numpy)
    x = RNG.randn(3, 57, 11).astype(np.float32) * 10
    data = c.encode(x)
    y = c.decode(data, x.shape)
    # error bounded by block max * 2^-(bits-1)
    bound = np.abs(x).max() * 2.0 ** -(c.bits - 1) + 1e-7
    assert np.abs(x - y).max() <= bound
    # fixed rate: ~bits/32 of float32 size + exponent overhead
    assert len(data) < x.nbytes * (c.bits / 32.0) * 1.2 + 64


def test_blockfloat_cross_backend_compatible():
    """Native and NumPy implement the identical BFC1 wire format."""
    cn = BlockFloatCodec(bits=7)
    cp = BlockFloatCodec(bits=7, force_numpy=True)
    x = RNG.randn(1000).astype(np.float32)
    assert cn.encode(x) == cp.encode(x)
    np.testing.assert_array_equal(cn.decode(cp.encode(x), x.shape),
                                  cp.decode(cn.encode(x), x.shape))


@pytest.mark.parametrize("force_numpy", [False, True])
def test_blockfloat_edge_cases(force_numpy):
    c = BlockFloatCodec(bits=8, force_numpy=force_numpy)
    for x in [np.zeros((64,), np.float32),
              np.zeros((0,), np.float32),
              np.array([1e-30, -1e30, 0, np.inf, -np.inf, np.nan],
                       np.float32),
              np.full((65,), 7.25, np.float32)]:
        y = c.decode(c.encode(x), x.shape)
        assert y.shape == x.shape
        finite = np.isfinite(x)
        # non-finite values are flushed to 0 by design
        assert np.isfinite(y).all()
        if finite.all() and x.size:
            assert np.abs(x - y).max() <= np.abs(x).max() * 2**-7 + 1e-7


@pytest.mark.parametrize("force_numpy", [False, True])
def test_lossless_roundtrip(force_numpy):
    c = LosslessCodec(force_numpy=force_numpy)
    for x in [RNG.randint(0, 255, 10_000).astype(np.uint8),
              np.tile(np.arange(100, dtype=np.int32), 50),
              RNG.randn(999).astype(np.float32),
              np.zeros((4096,), np.float32)]:
        y = c.decode(c.encode(x), x.shape, x.dtype)
        np.testing.assert_array_equal(x, y)


def test_lzb_compresses_redundancy():
    c = LosslessCodec()
    x = np.zeros((100_000,), np.uint8)
    assert len(c.encode(x)) < 3000  # ~3 bytes per max-length match token
    text = np.frombuffer(b"the quick brown fox " * 500, np.uint8)
    assert len(c.encode(text)) < text.size // 5


def test_lzb_cross_backend_compatible():
    cn = LosslessCodec()
    cp = LosslessCodec(force_numpy=True)
    x = np.tile(RNG.randint(0, 9, 100).astype(np.uint8), 30)
    # formats interchange even if greedy matches differ
    np.testing.assert_array_equal(
        cn.decode(cp.encode(x), x.shape, x.dtype), x)
    np.testing.assert_array_equal(
        cp.decode(cn.encode(x), x.shape, x.dtype), x)


@pytest.mark.parametrize("force_numpy", [False, True])
def test_pipeline_codec_stack(force_numpy):
    """The full lz(blockfloat(x)) stack the reference pioneered, symmetric."""
    c = PipelineCodec(bits=8, force_numpy=force_numpy)
    x = RNG.randn(32, 56, 56).astype(np.float32)
    y = c.decode(c.encode(x), x.shape)
    assert np.abs(x - y).max() <= np.abs(x).max() * 2**-7 + 1e-7


def test_corrupt_payloads_rejected():
    c = PipelineCodec()
    with pytest.raises(ValueError):
        c.decode(b"garbage!", (2,))
    bf = BlockFloatCodec()
    with pytest.raises(ValueError):
        bf.decode(b"NOPE" + b"\x00" * 20, (2,))
    lz = LosslessCodec()
    with pytest.raises(ValueError):
        lz.decode(b"LZB1\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff", (2,),
                  np.uint8)


def test_raw_codec():
    c = RawCodec()
    x = RNG.randn(5, 5).astype(np.float32)
    np.testing.assert_array_equal(c.decode(c.encode(x), x.shape, x.dtype), x)


@pytest.mark.parametrize("force_numpy", [False, True])
def test_blockfloat_extreme_exponents(force_numpy):
    """Exponent-byte saturation: huge values clamp toward 2^127, subnormal
    blocks flush toward 0 — never wrap (regression for the e+128 overflow)."""
    c = BlockFloatCodec(bits=8, force_numpy=force_numpy)
    huge = np.full((64,), 3e38, np.float32)
    got = c.decode(c.encode(huge), huge.shape)
    assert got.max() > 1e38  # same order of magnitude, not 1e-39
    tiny = np.full((64,), 1e-40, np.float32)
    got = c.decode(c.encode(tiny), tiny.shape)
    assert np.abs(got).max() < 1e-30  # flushed toward zero, not 1e+37


def test_blockfloat_extreme_cross_backend_identical():
    cn = BlockFloatCodec(bits=8)
    cp = BlockFloatCodec(bits=8, force_numpy=True)
    for x in (np.full((64,), 3e38, np.float32),
              np.full((64,), 1e-40, np.float32),
              np.array([2.0**-130, 2.0**127], np.float32)):
        assert cn.encode(x) == cp.encode(x)


@pytest.mark.parametrize("force_numpy", [False, True])
def test_hostile_size_headers_rejected_before_allocating(force_numpy):
    """A tiny payload whose header declares a multi-terabyte output must be
    rejected by validating against the caller's expected shape — not by
    attempting the allocation."""
    bomb_bf = (b"BFC1" + (2 ** 40).to_bytes(8, "little")
               + bytes([8, 0, 0, 0]))
    with pytest.raises(ValueError):
        BlockFloatCodec(bits=8, force_numpy=force_numpy).decode(
            bomb_bf, (64,))
    c = LosslessCodec(force_numpy=force_numpy)
    payload = c.encode(np.zeros(64, np.uint8))
    with pytest.raises(ValueError):
        c.decode(payload, (2 ** 40,), np.uint8)  # size mismatch, no alloc


def test_lzb_expansion_worst_case_bound():
    """Regression for a heap overflow: alternating [len-4 match at long
    distance][1-byte literal] expands to ~1.2x the input — more than the
    old all-literals bound (n + n/128) — and corrupted the heap on real
    multi-MB activation payloads.  The adversarial payload below forces
    that pattern; the encoder must stay within lzb_max_compressed_size,
    round-trip exactly, and agree bit-for-bit across backends."""
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, 60000, dtype=np.uint8).tobytes()
    b = bytearray(a)
    for j in range(0, len(b), 5):
        b[j] = (b[j] + 1) % 256  # break every 5th byte of the repeat
    payload = np.frombuffer(a + bytes(b), np.uint8)

    native_codec = LosslessCodec()
    py_codec = LosslessCodec(force_numpy=True)
    enc_n = native_codec.encode(payload)
    enc_p = py_codec.encode(payload)
    assert enc_n == enc_p  # backends share the exact format
    # the expansion is real (this is what broke the old bound) ...
    n = payload.size
    assert len(enc_n) > n + n // 128 + 24
    # ... and both directions stay correct
    for codec, enc in ((native_codec, enc_n), (py_codec, enc_p)):
        dec = codec.decode(enc, payload.shape, payload.dtype)
        np.testing.assert_array_equal(dec, payload)


def test_ensure_built_contract(tmp_path):
    """Shared native builder: builds when missing, rebuilds when the
    source is newer, refuses to bless a stale .so when the rebuild
    fails (callers then use their NumPy fallback, never stale code)."""
    import os
    import shutil
    import time

    from defer_tpu.utils._nativebuild import ensure_built

    if shutil.which("g++") is None:
        pytest.skip("no toolchain")
    src = tmp_path / "m.cpp"
    so = tmp_path / "m.so"
    src.write_text('extern "C" int f() { return 1; }\n')
    assert ensure_built(str(src), str(so))
    assert so.exists()
    first = so.stat().st_mtime_ns

    # fresh so, older-or-equal src: no rebuild
    assert ensure_built(str(src), str(so))
    assert so.stat().st_mtime_ns == first

    # newer src: rebuild happens (mtime moves)
    time.sleep(0.01)
    src.write_text('extern "C" int f() { return 2; }\n')
    os.utime(src, ns=(time.time_ns(), time.time_ns()))
    assert ensure_built(str(src), str(so))
    assert so.stat().st_mtime_ns > first

    # newer src that fails to compile: False, and no half-written temp
    time.sleep(0.01)
    src.write_text("this is not C++\n")
    os.utime(src, ns=(time.time_ns(), time.time_ns()))
    assert not ensure_built(str(src), str(so))
    assert not [p for p in tmp_path.iterdir() if ".build." in p.name]
