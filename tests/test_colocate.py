"""Colocated transport tier (docs/TRANSPORT.md): local-pipe semantics,
tier negotiation + fallback, fused device-tier stages, and the planner's
hop-tier map — the in-process halves of ``scripts/colocate_smoke.py``.
"""

import os
import socket
import threading

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.obs import REGISTRY
from defer_tpu.partition import fuse_stages
from defer_tpu.runtime.node import ChainDispatcher, StageNode
from defer_tpu.transport.channel import ChannelError
from defer_tpu.transport.framed import (K_CTRL, K_END, K_TENSOR,
                                        K_TENSOR_SEQ, PROTOCOL_VERSION,
                                        configure_socket, recv_frame,
                                        send_ctrl)
from defer_tpu.transport.local import (LocalPipe, grant_local, offer_local)


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


def _hist_count(name: str) -> int:
    return int(REGISTRY.histogram(name).summary().get("count", 0))


# ---------------------------------------------------------------------------
# LocalPipe semantics
# ---------------------------------------------------------------------------

def test_pipe_roundtrip_order_seq_ctrl_end():
    p = LocalPipe(depth=8)
    a = np.arange(6, dtype=np.float32)
    p.sender.send_ctrl({"cmd": "trace", "trace_id": "t"})
    p.sender.send(a)
    p.sender.send(a * 2, seq=7)
    p.sender.send_end()
    assert p.receiver.get(1.0) == (K_CTRL, {"cmd": "trace",
                                            "trace_id": "t"})
    kind, v = p.receiver.get(1.0)
    assert kind == K_TENSOR and v is a  # BY REFERENCE: zero copies
    kind, (seq, v) = p.receiver.get(1.0)
    assert kind == K_TENSOR_SEQ and seq == 7
    np.testing.assert_array_equal(v, a * 2)
    assert p.receiver.get(1.0) == (K_END, None)


def test_pipe_backpressure_is_bounded():
    """A slow consumer parks the producer after ``depth`` frames — the
    TCP backpressure contract, verbatim."""
    p = LocalPipe(depth=2)
    sent = []

    def produce():
        for i in range(6):
            p.sender.send(i)
            sent.append(i)
        p.sender.send_end()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    t.join(timeout=0.5)
    assert t.is_alive() and len(sent) <= 3  # parked on the full queue
    got = []
    while True:
        kind, v = p.receiver.get(2.0)
        if kind == K_END:
            break
        got.append(v)
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert got == list(range(6))  # in order, nothing dropped


def test_pipe_sender_death_fails_receiver():
    p = LocalPipe()
    p.sender.send(1)
    p.sender.detach()  # abandoned WITHOUT an END: a cut connection
    assert p.receiver.get(1.0) == (K_TENSOR, 1)
    with pytest.raises(ConnectionError):
        p.receiver.get(1.0)
    with pytest.raises(ConnectionError):
        p.receiver.get_nowait()


def test_pipe_clean_end_then_detach_is_noop():
    p = LocalPipe()
    p.sender.close()
    p.sender.detach()
    assert p.receiver.get(1.0) == (K_END, None)


def test_pipe_receiver_death_wakes_parked_sender():
    p = LocalPipe(depth=1)
    p.sender.send(0)
    err = []

    def produce():
        try:
            p.sender.send(1)  # parks on the full queue
        except ChannelError as e:
            err.append(e)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    t.join(timeout=0.3)
    assert t.is_alive()
    p.receiver.release_gauge()  # the consumer's stream loop exited
    t.join(timeout=5.0)
    assert not t.is_alive() and err, "parked producer never woke"


def test_pipe_gauge_reconciles():
    p = LocalPipe(depth=4)
    p.sender.send(0)
    p.receiver.bind_gauge("test.colo_gauge")
    g = REGISTRY.gauge("test.colo_gauge")
    base = g.value
    p.sender.send(1)
    assert g.value == base + 1
    p.receiver.get(1.0)
    assert g.value == base
    p.receiver.release_gauge()  # returns the remaining occupancy
    assert g.value == base - 1


# ---------------------------------------------------------------------------
# negotiation: grant validation + fallback
# ---------------------------------------------------------------------------

def _probe_msg(pipe: LocalPipe) -> dict:
    """A well-formed probe for ``pipe`` (registered in this process)."""
    from defer_tpu.transport import local as L
    return {"cmd": "tier_probe", "want": "local", "pid": os.getpid(),
            "proto": PROTOCOL_VERSION, "token": L._register(pipe)}


def test_grant_rejects_distinct_pid():
    msg = _probe_msg(LocalPipe())
    msg["pid"] = msg["pid"] + 1
    assert grant_local(msg) is None


def test_grant_rejects_version_mismatch():
    msg = _probe_msg(LocalPipe())
    msg["proto"] = PROTOCOL_VERSION + 1
    assert grant_local(msg) is None


def test_grant_rejects_unknown_token():
    msg = _probe_msg(LocalPipe())
    msg["token"] = "not-a-registered-token"
    assert grant_local(msg) is None


def test_grant_claims_exactly_once():
    pipe = LocalPipe()
    msg = _probe_msg(pipe)
    assert grant_local(msg) is pipe
    assert grant_local(msg) is None  # token single-use


def test_offer_refused_degrades_and_counts():
    """The sender side of the satellite contract: a refused offer comes
    back ("tcp", None) with ``transport.tier_fallback`` bumped — and the
    socket remains usable for the status-quo wire path."""
    a, b = socket.socketpair()

    def peer():
        kind, msg = recv_frame(b)
        assert kind == K_CTRL and msg["cmd"] == "tier_probe"
        send_ctrl(b, {"cmd": "tier_reply", "tier": "tcp"})

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    before = _counter("transport.tier_fallback")
    tier, pipe = offer_local(a)
    t.join(timeout=5.0)
    assert (tier, pipe) == ("tcp", None)
    assert _counter("transport.tier_fallback") == before + 1
    a.close()
    b.close()


def test_offer_granted_over_socketpair():
    a, b = socket.socketpair()
    got = {}

    def peer():
        kind, msg = recv_frame(b)
        got["pipe"] = grant_local(msg)
        send_ctrl(b, {"cmd": "tier_reply", "tier": "local"})

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    tier, pipe = offer_local(a)
    t.join(timeout=5.0)
    assert tier == "local" and pipe is not None
    assert got["pipe"] is pipe  # BOTH ends hold the same pipe
    arr = np.ones(3, np.float32)
    pipe.sender.send(arr)
    kind, v = got["pipe"].receiver.get(1.0)
    assert kind == K_TENSOR and v is arr
    a.close()
    b.close()


def test_configure_socket_skips_non_sockets():
    """Satellite: socket tuning must no-op (not raise) on non-TCP tiers'
    channel objects."""
    sentinel = object()
    assert configure_socket(sentinel) is sentinel
    pipe = LocalPipe()
    assert configure_socket(pipe.sender) is pipe.sender
    assert configure_socket(pipe.receiver) is pipe.receiver


# ---------------------------------------------------------------------------
# in-process chains: byte identity across tiers
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def _run_chain_inproc(stages, params, xs, *, tier, node_tiers=None,
                      accepts=None, codecs=None):
    """Thread-per-node chain; returns (outs, stats)."""
    n = len(stages)
    nodes = [StageNode(None, "127.0.0.1:0", None,
                       tier=(node_tiers or [tier] * n)[i],
                       tier_accept=True if accepts is None else accepts[i])
             for i in range(n)]
    addrs = [f"127.0.0.1:{nd.address[1]}" for nd in nodes]
    threads = [threading.Thread(target=nd.serve, daemon=True)
               for nd in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw", tier=tier)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0],
                    codecs=codecs, tiers=node_tiers or [tier] * n)
        outs = disp.stream(xs)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, stats


@pytest.fixture(scope="module")
def chain3(tiny):
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(3)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    outs, stats = _run_chain_inproc(stages, params, xs, tier="tcp")
    return g, params, stages, xs, outs, stats


def test_local_chain_byte_identical_no_codec_work(chain3):
    """All-colocated chain: every hop negotiates local, outputs are
    byte-identical to the all-TCP chain, and — the satellite regression
    — ZERO ``codec.*`` histogram samples are recorded on local hops
    (the raw path previously paid encode+decode even in-process).
    ``tier="local"`` pins the local rung: ``auto``'s top rung is now
    the device-resident ici tier (tests/test_ici.py), which would win
    every same-process hop here."""
    g, params, stages, xs, base, base_stats = chain3
    assert [s["tier"] for s in base_stats] == ["tcp"] * 3
    enc0, dec0 = _hist_count("codec.encode_s"), _hist_count("codec.decode_s")
    lf0 = _counter("transport.local_frames")
    outs, stats = _run_chain_inproc(stages, params, xs, tier="local")
    assert [s["tier"] for s in stats] == ["local"] * 3
    assert [s["tier_in"] for s in stats] == ["local"] * 3
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert _hist_count("codec.encode_s") == enc0, \
        "a local hop recorded codec encode samples"
    assert _hist_count("codec.decode_s") == dec0, \
        "a local hop recorded codec decode samples"
    # 4 hops (disp->s0->s1->s2->result) x len(xs) frames rode the pipes
    assert _counter("transport.local_frames") - lf0 == 4 * len(xs)


def test_mixed_tier_chain_byte_identical(chain3):
    g, params, stages, xs, base, _ = chain3
    outs, stats = _run_chain_inproc(
        stages, params, xs, tier="tcp",
        node_tiers=["local", "tcp", "local"])
    # hop s0->s1 local; s1->s2 stays tcp; s2->result refused by the
    # tcp-tier dispatcher (tier_accept=False) -> degrades to tcp
    assert [s["tier"] for s in stats] == ["local", "tcp", "tcp"]
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_claimed_colocation_degrades_to_tcp(chain3):
    """Satellite: a hop that CLAIMS colocation but fails the handshake
    (here: the peer refuses) silently degrades to tcp, the stream stays
    byte-identical, and ``transport.tier_fallback`` increments."""
    g, params, stages, xs, base, _ = chain3
    before = _counter("transport.tier_fallback")
    outs, stats = _run_chain_inproc(stages, params, xs, tier="local",
                                    accepts=[True, False, True])
    assert _counter("transport.tier_fallback") > before
    by_stage = {s["stage"]: s["tier"] for s in stats}
    assert by_stage[0] == "tcp"    # its offer to stage 1 was refused
    assert by_stage[1] == "local"  # stage 2 still granted
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_chain_byte_identical(chain3):
    """Device-tier hops fuse adjacent stages into ONE jit stage program
    — the hop (and its frames) ceases to exist, outputs unchanged."""
    g, params, stages, xs, base, _ = chain3
    fused, groups = fuse_stages(stages, ["device", "local"])
    assert groups == [[0, 1], [2]] and len(fused) == 2
    assert fused[0].node_names == stages[0].node_names + stages[1].node_names
    outs, stats = _run_chain_inproc(fused, params, xs, tier="auto")
    assert [s["stage"] for s in stats] == [0, 1]
    assert sorted(s["processed"] for s in stats) == [len(xs)] * 2
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fuse_stages_validates():
    g = resnet_tiny()
    stages = partition(g, num_stages=3)
    with pytest.raises(ValueError):
        fuse_stages(stages, ["device"])  # wrong arity
    same, groups = fuse_stages(stages, ["tcp", "local"])
    assert len(same) == 3 and groups == [[0], [1], [2]]


# ---------------------------------------------------------------------------
# planner: the hop-tier map
# ---------------------------------------------------------------------------

def _fat_boundary_model():
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel

    b = GraphBuilder("fatcut")
    x = b.input((4096,))
    for i in range(3):
        x = b.add(ops.Dense(4096), x, name=f"d{i}")
    x = b.add(ops.Dense(8), x, name="head")
    g = b.build()
    costs = {"d0": 1e-3, "d1": 1e-3, "d2": 1e-3, "head": 1e-4}
    # slow link: every 4096-float boundary costs ~16 ms on the wire —
    # comm-bound unless the tier map zeroes it
    return g, StageCostModel(g, gen="v4", link_bw_s=1e6, node_costs=costs)


def test_solver_exploits_hop_tier_map():
    from defer_tpu.plan import plan_from_json, solve

    g, cm = _fat_boundary_model()
    p_tcp = solve(g, 3, cm)
    tiers = {c: "local" for c in ("d0", "d1", "d2")}
    p_loc = solve(g, 3, cm, hop_tiers=tiers)
    assert p_loc.bottleneck_s < p_tcp.bottleneck_s  # STRICT: comm-bound
    assert set(p_loc.codecs) == {"local"}
    assert p_loc.hop_tiers == ["local"] * 2
    assert p_tcp.hop_tiers == ["tcp"] * 2
    doc = p_loc.to_json()
    assert doc["hop_tiers"] == ["local", "local"]
    assert plan_from_json(doc).hop_tiers == ["local", "local"]


def test_device_tier_is_free_local_pays_memory_bw():
    g, cm = _fat_boundary_model()
    cm_d = cm.with_hop_tiers({"d1": "device"})
    cm_l = cm.with_hop_tiers({"d1": "local"})
    assert cm_d.comm_seconds("d1", "device") == 0.0
    local_s = cm_l.comm_seconds("d1", "local")
    assert 0.0 < local_s < cm.best_codec("d1")[1]
    assert cm_l.best_codec("d1") == ("local", local_s)
    assert cm.best_codec("d1")[0] != "local"  # untiered: wire argmin


def test_replicated_fan_hops_never_tiered():
    """A colocated tier only holds when neither side fans — the runtime
    constraint mirrored into the cost model."""
    g, cm = _fat_boundary_model()
    cm = cm.with_hop_tiers({"d1": "local"})
    name, s = cm.best_codec_replicated("d1", 1, 1)
    assert name == "local"
    name2, s2 = cm.best_codec_replicated("d1", 2, 1)
    assert name2 != "local" and s2 > s


def test_replan_preserves_hop_tiers():
    from defer_tpu.plan import replan, solve

    g, cm = _fat_boundary_model()
    tiers = {c: "local" for c in ("d0", "d1", "d2")}
    plan = solve(g, 3, cm, hop_tiers=tiers)
    rp = replan(g, plan, {0: 2e-3, 1: 1e-3, 2: 1e-3},
                cm.with_hop_tiers(tiers))
    assert set(rp.new_plan.hop_tiers) == {"local"}
    assert set(rp.old_plan_corrected.hop_tiers) == {"local"}
