"""Branched (DAG) chain runtime tests: in-process thread-node
deployments of fork/join topologies must be byte-identical to the serial
composition of their own stage programs, with per-branch attribution in
stats — plus the loud non-composition rules (replicas / hop tiers) and
the ``chain --dag`` CLI guard rails (docs/TRANSPORT.md)."""

import threading

import jax
import numpy as np
import pytest

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.graph import ops
from defer_tpu.models import moe_branched_tiny
from defer_tpu.plan import StageCostModel, solve_dag
from defer_tpu.runtime.node import ChainDispatcher, StageNode, run_dag_chain
from defer_tpu.runtime.topology import ChainTopology
from defer_tpu.utils.export import export_stage_bytes, load_stage_program


def two_branch_graph():
    """input -> stem -> {b0: 2 Dense, b1: 1 Dense, residual} -> Add ->
    out: one region with an empty branch, small enough for fast
    exports."""
    b = GraphBuilder("twobranch")
    x = b.input((8,))
    x = b.add(ops.Dense(8), x, name="stem")
    p = b.add(ops.Dense(8), x, name="b0n0")
    p = b.add(ops.Dense(8), p, name="b0n1")
    q = b.add(ops.Dense(8), x, name="b1n0")
    x = b.add(ops.Add(), [x, p, q], name="join")
    x = b.add(ops.Dense(4), x, name="head")
    return b.build()


def deploy_inproc(graph, topo, params, xs, *, batch=1, streams=1):
    """Thread-per-vertex deployment; returns (outs of the LAST stream,
    stats rows)."""
    stages = topo.stage_specs(graph)
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in topo.vertices]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    try:
        disp.deploy_topology(topo, stages, params, addrs, batch=batch)
        for _ in range(streams):
            outs = disp.stream(xs)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, stats


def serial_reference(graph, topo, params, xs, *, batch=1):
    """Serial composition of the deployment's own stage programs — the
    exact byte-identity contract (the fused single program differs in
    XLA fusion by ~1e-6; `graph.apply` closeness is asserted separately)."""
    progs = [load_stage_program(export_stage_bytes(s, params, batch=batch))
             for s in topo.stage_specs(graph)]
    graph_input = topo.entry.inputs[0]
    outs = []
    for x in xs:
        vals = {}
        for v, p in zip(topo.vertices, progs):
            ins = [x if name == graph_input else vals[name]
                   for name in v.inputs]
            vals[v.output] = np.asarray(p(*ins))
        outs.append(vals[topo.exit.output])
    return outs


def solved_topology(graph, *, heavy, budget):
    costs = {n: heavy.get(n, 1e-6) for n in graph.topo_order}
    cm = StageCostModel(graph, gen="v5e", link_bw_s=1e12,
                        node_costs=costs)
    plan = solve_dag(graph, cm, num_nodes=budget)
    assert plan.parallel_regions, plan.to_json()
    return ChainTopology.from_json(plan.topology_json())


def test_branched_chain_byte_identity_two_branch():
    g = two_branch_graph()
    params = g.init(jax.random.key(0))
    topo = solved_topology(
        g, heavy={"b0n0": 1e-3, "b0n1": 1e-3, "b1n0": 2e-3}, budget=5)
    assert any(v.fan == "broadcast" for v in topo.vertices)
    join = next(v for v in topo.vertices if v.join >= 2)
    assert join.join == 3          # two real branches + residual skip
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1, 8)).astype(np.float32)
          for _ in range(8)]
    outs, stats = deploy_inproc(g, topo, params, xs)
    ref = serial_reference(g, topo, params, xs)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, np.asarray(b))
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(fwd(params, x)), y,
                                   rtol=1e-5, atol=1e-5)
    # every branch vertex saw every frame (broadcast, not round-robin),
    # and stats attribute rows to their branch path
    # path 0 is the residual skip (a direct fork->join channel, no
    # vertex); the two real branches ride paths 1 and 2
    per_branch = {s["branch"]: s["processed"] for s in stats
                  if s.get("branch") is not None}
    assert per_branch == {1: len(xs), 2: len(xs)}
    assert any(s.get("join") == 3 for s in stats)


def test_branched_chain_multi_stream_and_order():
    """Several stream() calls ride one deployment (the fork's shared
    sequence stamp keeps advancing), outputs strictly in input order."""
    g = two_branch_graph()
    params = g.init(jax.random.key(0))
    topo = solved_topology(
        g, heavy={"b0n0": 1e-3, "b0n1": 1e-3, "b1n0": 2e-3}, budget=5)
    # distinguishable frames: ordering mistakes change outputs
    xs = [np.full((1, 8), i, np.float32) for i in range(6)]
    outs, _ = deploy_inproc(g, topo, params, xs, streams=3)
    ref = serial_reference(g, topo, params, xs)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, np.asarray(b))


def test_branched_chain_byte_identity_moe_branched_tiny():
    """The expert-parallel MoE scenario: both 4-expert regions fanned
    out (11 vertices), byte-identical to the serial forward."""
    g = moe_branched_tiny(seq_len=8)
    params = g.init(jax.random.key(0))
    heavy = {n: 1e-3 for n in g.topo_order
             if n.startswith("block_") or "_e" in n}
    topo = solved_topology(g, heavy=heavy, budget=12)
    assert sum(1 for v in topo.vertices if v.join >= 2) == 2
    rng = np.random.default_rng(0)
    xs = [rng.integers(0, 100, (1, 8)).astype(np.int32)
          for _ in range(4)]
    outs, stats = deploy_inproc(g, topo, params, xs)
    ref = serial_reference(g, topo, params, xs)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, np.asarray(b))
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(np.asarray(fwd(params, x)), y,
                                   rtol=1e-4, atol=1e-4)
    branch_rows = [s for s in stats if s.get("branch") is not None]
    assert len(branch_rows) == 8   # 4 experts x 2 layers
    assert all(s["processed"] == len(xs) for s in branch_rows)


def test_branched_chain_byte_identity_inception_tiny():
    """The multi-branch vision scenario: 306 nodes, 5 vertices around
    the mixed_3 reduction region (scripts/dag_smoke.py measures the
    same deployment's speedup; this asserts just the identity)."""
    from defer_tpu.models import inception_tiny

    g = inception_tiny()
    params = g.init(jax.random.key(0))
    heavy = {}
    from defer_tpu.graph.analysis import branch_regions
    region = next(r for r in branch_regions(g) if r.join == "mixed_3")
    for b in region.branches[:2]:
        for n in b.nodes:
            heavy[n] = 1e-3
    topo = solved_topology(g, heavy=heavy, budget=5)
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1, 75, 75, 3)).astype(np.float32)
          for _ in range(3)]
    outs, _ = deploy_inproc(g, topo, params, xs)
    ref = serial_reference(g, topo, params, xs)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, np.asarray(b))


@pytest.mark.slow
def test_run_dag_chain_real_processes():
    """run_dag_chain spawns the branched topology as real OS `defer_tpu
    node` processes (the `chain --dag` path) — byte-identical to the
    serial composition of its own stage programs."""
    g = two_branch_graph()
    params = g.init(jax.random.key(0))
    topo = solved_topology(
        g, heavy={"b0n0": 1e-3, "b0n1": 1e-3, "b1n0": 2e-3}, budget=5)
    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1, 8)).astype(np.float32)
          for _ in range(5)]
    stats: list = []
    outs = run_dag_chain(g, params, xs, topology=topo, stats_out=stats)
    ref = serial_reference(g, topo, params, xs)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, np.asarray(b))
    per_branch = {s["branch"]: s["processed"] for s in stats
                  if s.get("branch") is not None}
    assert per_branch == {1: len(xs), 2: len(xs)}


def test_run_dag_chain_rejects_replicas_and_tiers():
    """Branch fan machinery and replica/colocation machinery own
    different sequence namespaces: composing them fails loudly BEFORE
    any process spawns."""
    g = two_branch_graph()
    params = g.init(jax.random.key(0))
    topo = solved_topology(
        g, heavy={"b0n0": 1e-3, "b0n1": 1e-3, "b1n0": 2e-3}, budget=5)
    with pytest.raises(ValueError, match="replicas"):
        run_dag_chain(g, params, [], topology=topo,
                      replicas={1: 2})
    with pytest.raises(ValueError, match="hop_tiers"):
        run_dag_chain(g, params, [], topology=topo,
                      hop_tiers={"stem": "local"})


def test_node_role_flags_validated():
    with pytest.raises(ValueError, match="join_in"):
        StageNode(None, "127.0.0.1:0", None, join_in=1)
    with pytest.raises(ValueError, match="fan_mode"):
        StageNode(None, "127.0.0.1:0", None, fan_mode="multicast")
    with pytest.raises(ValueError, match="replica fan-in"):
        StageNode(None, "127.0.0.1:0", None, join_in=2, fan_in=2)


def test_cli_chain_dag_guard_rails(capsys):
    from defer_tpu.cli import main
    with pytest.raises(SystemExit, match="replicas"):
        main(["chain", "--model", "moe_branched_tiny", "--dag",
              "--replicas", "stage1=2"])
    with pytest.raises(SystemExit, match="wire-framed"):
        main(["chain", "--model", "moe_branched_tiny", "--dag",
              "--hop-tiers", "local"])
    with pytest.raises(SystemExit, match="linear planner"):
        main(["chain", "--model", "moe_branched_tiny", "--dag",
              "--cuts", "block_0"])


def test_monitor_renders_branch_column(capsys):
    """The monitor's BR column shows stageK.bJ / join rows, so the
    bottleneck highlight names a branch instead of a flattened index."""
    from defer_tpu.cli import _render_monitor

    def row(stage, **kw):
        d = {"stage": stage, "replica": None, "branch": None,
             "join": None, "tier": "tcp", "alive": True, "addr": "a:1",
             "infer_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
             "throughput_per_s": 10.0, "rx_q": 0, "tx_q": 0,
             "rx_hi": 0, "tx_hi": 0, "inflight": 0,
             "rx_bytes_per_s": 0.0, "tx_bytes_per_s": 0.0,
             "processed": 5}
        d.update(kw)
        return d

    rows = [row(0), row(1, branch=1), row(2, branch=2),
            row(3, join=3)]
    _render_monitor(rows, 1, [], {}, clear=False)
    out = capsys.readouterr().out
    assert "BR" in out.splitlines()[0]
    assert " b1 " in out and " b2 " in out and " j3 " in out
    # the highlighted bottleneck row is the branch vertex (non-tty mode
    # appends the marker instead of inverting)
    marked = [ln for ln in out.splitlines() if "<- bottleneck" in ln]
    assert len(marked) == 1 and " b1 " in marked[0]


def test_cluster_rows_carry_branch_ident():
    """ClusterView rows surface the node ident's branch/join fields —
    what a live branched chain pushes (docs/OBSERVABILITY.md)."""
    from defer_tpu.obs.cluster import ClusterView

    view = ClusterView()
    payload = {"node": {"stage": 2, "name": "g/stage2.b1", "replica": None,
                        "branch": 1, "join": 0, "fan_in": 1, "port": 1,
                        "codec": "raw", "tier": "tcp", "tier_in": None},
               "processed": 3, "queues": {}, "latency": {},
               "counters": {}}
    view.ingest(payload, "127.0.0.1:1")
    (r,) = view.rows()
    assert r["branch"] == 1 and r["join"] == 0 and r["stage"] == 2


def test_cli_oversubscribed_linear_names_merges(capsys):
    from defer_tpu.cli import main
    for argv in (["plan", "--model", "moe_branched_tiny",
                  "--stages", "10"],
                 ["partition", "--model", "moe_branched_tiny",
                  "--stages", "10"]):
        with pytest.raises(SystemExit) as ei:
            main(argv)
        msg = str(ei.value)
        assert "moe_0" in msg and "moe_1" in msg and "--dag" in msg
