"""DAG planner tests: branch-region analysis, the critical-path solver
vs its brute-force oracle, stage-graph hop-tier validation, and the
plan/topology JSON round trip (docs/PLANNER.md)."""

import itertools

import jax
import numpy as np
import pytest

from defer_tpu.graph.analysis import (branch_regions, dag_cut_points,
                                      linear_cut_shortage,
                                      segment_cut_points,
                                      valid_cut_points)
from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.graph import ops
from defer_tpu.models import inception_tiny, moe_branched_tiny, moe_tiny
from defer_tpu.plan import StageCostModel, brute_force_dag, solve, solve_dag
from defer_tpu.plan.dag import dag_plan_from_json
from defer_tpu.runtime.topology import ChainTopology


def branchy(widths, depths, *, residual=(), name="branchy"):
    """A chain of fork/join regions: region i forks to ``widths[i]``
    Dense branches of ``depths[i]`` nodes each (plus a residual skip
    when i is in ``residual``), joined by Add, with a trunk node between
    regions."""
    b = GraphBuilder(name)
    x = b.input((8,))
    x = b.add(ops.Dense(8), x, name="stem")
    for i, (w, d) in enumerate(zip(widths, depths)):
        branches = []
        for p in range(w):
            y = x
            for k in range(d):
                y = b.add(ops.Dense(8), y, name=f"r{i}b{p}n{k}")
            branches.append(y)
        skip = [x] if i in residual else []
        x = b.add(ops.Add(), skip + branches, name=f"join{i}")
        x = b.add(ops.Dense(8), x, name=f"trunk{i}")
    return b.build()


# -- branch-region analysis -------------------------------------------------


def test_branch_regions_inception():
    g = inception_tiny()
    regions = branch_regions(g)
    joins = [r.join for r in regions]
    assert joins == [f"mixed_{i}" for i in range(11)]
    widths = {r.join: r.width for r in regions}
    assert widths["mixed_0"] == 4     # block A: four parallel branches
    assert widths["mixed_3"] == 3     # grid reduction: three
    for r in regions:
        # branches partition the strict interior, pairwise disjoint
        inner = [n for b in r.branches for n in b.nodes]
        assert len(inner) == len(set(inner))
        assert all(not b.empty for b in r.branches)


def test_branch_regions_residual_skip():
    """moe_branched's residual Add input IS the fork: an empty branch."""
    g = moe_branched_tiny()
    regions = branch_regions(g)
    assert [(r.fork, r.join, r.width) for r in regions] == [
        ("block_0", "moe_0", 5), ("block_1", "moe_1", 5)]
    for r in regions:
        assert r.branches[0].empty           # the residual skip
        assert r.branches[0].out == r.fork
        assert all(not b.empty for b in r.branches[1:])


def test_branch_regions_rejects_shared_intermediate():
    """An interior node feeding two merge inputs is not separable: the
    block stays indivisible to every planner (no region)."""
    b = GraphBuilder("shared")
    x = b.input((8,))
    x = b.add(ops.Dense(8), x, name="fork")
    # `mid` feeds both join inputs, and the direct fork->q edge keeps
    # it from being an articulation of its own
    mid = b.add(ops.Dense(8), x, name="mid")
    p = b.add(ops.Dense(8), mid, name="p")
    q = b.add(ops.Add(), [mid, x], name="q")
    x = b.add(ops.Concat(), [p, q], name="join")
    g = b.build()
    assert branch_regions(g) == []
    # ...but the inner Concat of a block_c-style nested fork still
    # leaves the OUTER block a valid region (sub-branches ride inside
    # one branch body)
    assert branch_regions(branchy([2], [2]))[0].width == 2


def test_branch_regions_rejects_duplicate_fork_input():
    """A merge consuming the fork tensor TWICE is a duplicate input,
    not two residual skips: the planner would emit a topology whose
    join cannot tell the two direct fork edges apart, so the block
    stays indivisible (no region)."""
    b = GraphBuilder("dupfork")
    x = b.input((8,))
    x = b.add(ops.Dense(8), x, name="fork")
    p = b.add(ops.Dense(8), x, name="p")
    x = b.add(ops.Add(), [x, x, p], name="join")
    g = b.build()
    assert branch_regions(g) == []


def test_fused_moe_has_no_regions():
    assert branch_regions(moe_tiny()) == []


def test_segment_and_dag_cut_points():
    g = branchy([2], [3])
    (r,) = branch_regions(g)
    # inside a 3-node branch body the first two nodes are valid cuts
    for b in r.branches:
        assert segment_cut_points(g, b.nodes, r.fork) == list(b.nodes[:2])
    dag_cuts = dag_cut_points(g)
    assert set(valid_cut_points(g)) < set(dag_cuts)
    assert "r0b0n0" in dag_cuts and "r0b1n1" in dag_cuts


def test_linear_cut_shortage_names_merges():
    g = moe_branched_tiny()
    assert linear_cut_shortage(g, 7) is None
    msg = linear_cut_shortage(g, 10)
    assert "moe_0" in msg and "moe_1" in msg
    assert "--dag" in msg
    # a non-branching chain reports the plain shortage, no DAG pointer
    b = GraphBuilder("chain3")
    x = b.input((8,))
    for i in range(3):
        x = b.add(ops.Dense(8), x, name=f"d{i}")
    msg = linear_cut_shortage(b.build(), 9)
    assert "9 stages" in msg and "--dag" not in msg


# -- solver vs brute force --------------------------------------------------


def _random_cost_model(g, rng):
    costs = {n: float(rng.uniform(1e-4, 2e-3)) for n in g.topo_order}
    link = float(rng.choice([1e7, 1e9, 1e11]))
    return StageCostModel(g, gen="v5e", link_bw_s=link, node_costs=costs)


def _key(plan):
    return (round(plan.bottleneck_s, 12),
            round(plan.critical_path_s, 12), plan.num_nodes)


@pytest.mark.parametrize("shape", [
    ([2], [1], ()), ([2], [2], ()), ([3], [1], (0,)),
    ([2, 2], [1, 2], (1,)), ([2, 3], [2, 1], ())])
def test_solve_dag_matches_brute_force(shape):
    widths, depths, residual = shape
    g = branchy(widths, depths, residual=residual)
    rng = np.random.default_rng(sum(widths) * 7 + sum(depths))
    for trial in range(3):
        cm = _random_cost_model(g, rng)
        for budget in (1, 2, 4, 6):
            got = solve_dag(g, cm, num_nodes=budget)
            want = brute_force_dag(g, cm, num_nodes=budget)
            assert _key(got) == _key(want), (
                f"budget {budget} trial {trial}: DP {_key(got)} vs "
                f"brute {_key(want)}")


def test_solve_dag_prefers_branching_when_compute_bound():
    g = branchy([2], [1])
    costs = {n: 1e-6 for n in g.topo_order}
    costs["r0b0n0"] = costs["r0b1n0"] = 1e-2   # two fat parallel branches
    cm = StageCostModel(g, gen="v5e", link_bw_s=1e12, node_costs=costs)
    plan = solve_dag(g, cm, num_nodes=4)
    assert plan.parallel_regions == [
        {"fork": "stem", "join": "join0", "paths": 2}]
    assert plan.bottleneck_s == pytest.approx(1e-2, rel=1e-3)
    # the linear plan at the same budget serializes both branches
    lin = min((solve(g, s, cm) for s in range(1, 4)),
              key=lambda p: p.bottleneck_s)
    assert plan.bottleneck_s < lin.bottleneck_s


def test_solve_dag_degenerates_to_linear():
    """No separable regions, or a 1-node budget: the DAG plan is the
    linear chain plan, topology included."""
    g = moe_tiny()
    cm = StageCostModel(g, gen="v5e")
    plan = solve_dag(g, cm, num_nodes=3)
    assert plan.parallel_regions == []
    assert all(v.fan == "unicast" and v.join == 0 and v.branch is None
               for v in plan.vertices)
    one = solve_dag(branchy([2], [2]), StageCostModel(
        branchy([2], [2]), gen="v5e"), num_nodes=1)
    assert one.num_stages == 1


def test_dag_plan_json_round_trip():
    g = branchy([2], [2], residual=(0,))
    costs = {n: 1e-3 for n in g.topo_order}
    cm = StageCostModel(g, gen="v5e", link_bw_s=1e12, node_costs=costs)
    plan = solve_dag(g, cm, num_nodes=5)
    assert plan.parallel_regions
    doc = plan.to_json()
    back = dag_plan_from_json(doc)
    assert _key(back) == _key(plan)
    assert [v.label for v in back.vertices] == doc["labels"]
    # the embedded topology validates and deploys the same shape
    topo = ChainTopology.from_json(doc)
    assert len(topo) == plan.num_stages
    assert sum(1 for v in topo.vertices if v.fan == "broadcast") == 1
    assert sum(1 for v in topo.vertices if v.join >= 2) == 1


# -- stage-graph hop tiers (loud-miss policy) -------------------------------


def test_dag_hop_tiers_accept_branch_internal_cuts():
    g = branchy([2], [2])
    costs = {n: 1e-3 for n in g.topo_order}
    cm = StageCostModel(g, gen="v5e", link_bw_s=1e12, node_costs=costs)
    # r0b0n0 is a branch-internal cut: invalid for the LINEAR planner...
    with pytest.raises(ValueError, match="not valid cut points"):
        cm.with_hop_tiers({"r0b0n0": "local"})
    # ...but a real stage-graph boundary for the DAG planner
    plan = solve_dag(g, cm, num_nodes=6,
                     hop_tiers={"r0b0n0": "local"})
    assert plan.num_stages >= 1
    # unknown keys still miss loudly under the widened namespace
    with pytest.raises(ValueError, match="not valid cut points"):
        solve_dag(g, cm, num_nodes=6, hop_tiers={"nope": "local"})


def test_dag_hop_tiers_reject_fan_boundaries():
    """A colocation claim on a fork or a branch output is rejected like
    any fan hop — the ordered branch machinery is wire-framed."""
    g = branchy([2], [2])
    costs = {n: 1e-3 for n in g.topo_order}
    cm = StageCostModel(g, gen="v5e", link_bw_s=1e12, node_costs=costs)
    with pytest.raises(ValueError, match="wire-framed"):
        solve_dag(g, cm, num_nodes=6, hop_tiers={"stem": "local"})
    with pytest.raises(ValueError, match="wire-framed"):
        solve_dag(g, cm, num_nodes=6, hop_tiers={"r0b1n1": "device"})
    # a tcp claim on the fork or a branch output is fine (it IS the
    # wire tier those hops ride)
    solve_dag(g, cm, num_nodes=6, hop_tiers={"stem": "tcp"})
    solve_dag(g, cm, num_nodes=6, hop_tiers={"r0b1n1": "tcp"})


# -- topology validation ----------------------------------------------------


def _vertex(vid, **kw):
    from defer_tpu.runtime.topology import TopoVertex
    d = dict(vid=vid, nodes=(f"n{vid}",), inputs=(f"i{vid}",),
             output=f"n{vid}", next=())
    d.update(kw)
    return TopoVertex(**d)


def test_topology_validates_structure():
    # fan/next mismatch
    with pytest.raises(ValueError, match="broadcast"):
        ChainTopology([_vertex(0, next=(1, 2)),
                       _vertex(1, next=(3,)), _vertex(2, next=(3,)),
                       _vertex(3, join=2, inputs=("a", "b"))])
    # join in-degree must carry distinct path labels
    with pytest.raises(ValueError, match="join"):
        ChainTopology([
            _vertex(0, next=(1, 2), fan="broadcast"),
            _vertex(1, next=(3,), branch=0),
            _vertex(2, next=(3,), branch=0),       # duplicate path label
            _vertex(3, join=2, inputs=("a", "b"))])
    # the valid version passes and labels spans stageK.bJ
    topo = ChainTopology([
        _vertex(0, next=(1, 2), fan="broadcast"),
        _vertex(1, next=(3,), branch=0),
        _vertex(2, next=(3,), branch=1),
        _vertex(3, join=2, inputs=("a", "b"))])
    assert [v.label for v in topo.vertices] == [
        "stage0", "stage1.b0", "stage2.b1", "stage3"]
    back = ChainTopology.from_json(topo.to_json())
    assert [v.label for v in back.vertices] == [
        "stage0", "stage1.b0", "stage2.b1", "stage3"]


def test_topology_rejects_unlabeled_join_upstream():
    """A hand-written topology feeding a join from a plain unicast,
    non-branch vertex must fail with the named error, not a raw
    TypeError from sorting a None path label."""
    with pytest.raises(ValueError, match="path label"):
        ChainTopology([
            _vertex(0, next=(1, 2), fan="broadcast"),
            _vertex(1, next=(3,), branch=0),
            _vertex(2, next=(3,)),                  # no branch label
            _vertex(3, join=2, inputs=("a", "b"))])


def test_topology_rejects_multiple_entries_or_exits():
    with pytest.raises(ValueError, match="entry"):
        ChainTopology([_vertex(0, next=(2,)), _vertex(1, next=(2,)),
                       _vertex(2, join=2, inputs=("a", "b"))])
    with pytest.raises(ValueError, match="exit"):
        ChainTopology([_vertex(0), _vertex(1)])  # both have next=()
