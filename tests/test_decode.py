"""Pipelined KV-cache decoding: equivalence against reference decoders.

Ground truth #1 is an incremental single-device greedy loop built from the
same ``CausalTransformerBlock.decode`` ops — the pipelined engine must match
it token-for-token exactly (same math, same op order, just scheduled across
the stage ring).  Ground truth #2 is full-sequence recompute through
``graph.apply`` (a different reduction order, so ids must agree but logits
only approximately).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu.models import gpt_stage_cuts, gpt_tiny
from defer_tpu.models.gpt import CausalTransformerBlock
from defer_tpu.runtime.decode import PipelinedDecoder, _split_blocks

VOCAB = 97
MAX_LEN = 24


def incremental_greedy(graph, params, prompt, t_tok, max_len):
    """Single-device KV-cache greedy decode via the same block ops."""
    nodes = graph.nodes
    blocks = [nm for nm in graph.topo_order if nm.startswith("block_")]
    b, plen = prompt.shape
    op0 = nodes[blocks[0]].op
    d = nodes[blocks[0]].out_spec.shape[-1]
    # head-major KV-head cache contract (kv < num_heads under GQA)
    shape = (b, op0.kv_heads, max_len + 1, d // op0.num_heads)
    kc = {nm: jnp.zeros(shape) for nm in blocks}
    vc = {nm: jnp.zeros(shape) for nm in blocks}
    out = np.zeros((b, t_tok), np.int64)
    out[:, :plen] = prompt
    for p in range(t_tok - 1):
        tok = jnp.asarray(out[:, p], jnp.int32)
        x = nodes["embeddings"].op.embed_at(params["embeddings"], tok, p)
        for nm in blocks:
            x, kc[nm], vc[nm] = nodes[nm].op.decode(
                params[nm], x, kc[nm], vc[nm], p)
        h = nodes["final_ln"].op.apply(params["final_ln"], x)
        logits = nodes["lm_head"].op.apply(params["lm_head"], h)
        nxt = np.asarray(jnp.argmax(logits.astype(jnp.float32), -1))
        if p + 1 >= plen:
            out[:, p + 1] = nxt
    return out


def full_recompute_greedy(graph, params, prompt, t_tok):
    """Greedy decode by re-running the whole causal graph every token."""
    cur = np.asarray(prompt, np.int64)
    while cur.shape[1] < t_tok:
        logits = graph.apply(params, jnp.asarray(cur, jnp.int32))
        nxt = np.asarray(jnp.argmax(logits[:, -1].astype(jnp.float32), -1))
        cur = np.concatenate([cur, nxt[:, None].astype(np.int64)], 1)
    return cur


@pytest.fixture(scope="module")
def model():
    graph = gpt_tiny(seq_len=MAX_LEN, vocab=VOCAB)
    params = graph.init(jax.random.key(7))
    return graph, params


@pytest.fixture(scope="module")
def prompt():
    rng = np.random.default_rng(3)
    return rng.integers(0, VOCAB, size=(8, 5)).astype(np.int32)


@pytest.mark.parametrize("num_stages,microbatch", [(4, 2), (2, 4), (1, 8)])
def test_pipelined_matches_incremental(model, prompt, num_stages, microbatch):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=num_stages,
                           microbatch=microbatch, max_len=MAX_LEN)
    got = dec.generate(prompt, max_new_tokens=9)
    want = incremental_greedy(graph, params, prompt, 5 + 9, MAX_LEN)
    np.testing.assert_array_equal(got, want)


def test_pipelined_matches_full_recompute(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    got = dec.generate(prompt, max_new_tokens=8)
    want = full_recompute_greedy(graph, params, prompt, 5 + 8)
    np.testing.assert_array_equal(got, want)


def test_partial_group_occupancy(model, prompt):
    """B < num_stages*microbatch: unused slots are bubbles, results exact."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    got = dec.generate(prompt[:4], max_new_tokens=6)
    want = incremental_greedy(graph, params, prompt[:4], 5 + 6, MAX_LEN)
    np.testing.assert_array_equal(got, want)


def test_prompt_only_roundtrip(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    out = dec.generate(prompt, max_new_tokens=0)
    np.testing.assert_array_equal(out, prompt)


def test_chunked_dispatch_matches_single_dispatch(model, prompt):
    """token_chunk splits the scan into several dispatches with carried
    state; results must be identical, and one compiled program must serve
    different generation lengths."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    whole = dec.generate(prompt, max_new_tokens=9)
    chunked = dec.generate(prompt, max_new_tokens=9, token_chunk=2)
    np.testing.assert_array_equal(whole, chunked)
    n_compiled = len(dec._decode_fns)
    shorter = dec.generate(prompt, max_new_tokens=4, token_chunk=2)
    assert len(dec._decode_fns) == n_compiled  # same program, shorter run
    np.testing.assert_array_equal(shorter, whole[:, : 5 + 4])


def test_sampling_deterministic_and_chunking_invariant(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    a = dec.generate(prompt, max_new_tokens=8, temperature=1.0, seed=11)
    b = dec.generate(prompt, max_new_tokens=8, temperature=1.0, seed=11)
    np.testing.assert_array_equal(a, b)          # same seed -> same draw
    c = dec.generate(prompt, max_new_tokens=8, temperature=1.0, seed=11,
                     token_chunk=3)
    np.testing.assert_array_equal(a, c)          # chunking-invariant
    d = dec.generate(prompt, max_new_tokens=8, temperature=1.0, seed=12)
    assert not np.array_equal(a, d)              # different seed differs
    assert (a[:, 5:] < VOCAB).all() and (a[:, 5:] >= 0).all()
    e = dec.generate(prompt, max_new_tokens=8, temperature=1.0, seed=11,
                     top_k=5)
    assert e.shape == a.shape


def test_eos_early_stop(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    ref = dec.generate(prompt, max_new_tokens=10)
    # pick the token the greedy run emits first as the "EOS" so it triggers
    eos = int(ref[0, 5])
    got = dec.generate(prompt, max_new_tokens=10, eos_id=eos, token_chunk=2)
    assert got.shape == ref.shape
    for r in range(got.shape[0]):
        gen = got[r, 5:]
        hits = np.where(gen == eos)[0]
        if hits.size:                      # everything after first EOS is EOS
            assert (gen[hits[0]:] == eos).all()
    # rows must agree with the unconstrained run up to their first EOS
    row0 = ref[0, 5:]
    stop = np.where(row0 == eos)[0][0]
    np.testing.assert_array_equal(got[0, 5: 5 + stop + 1],
                                  ref[0, 5: 5 + stop + 1])


@pytest.mark.parametrize("num_stages,microbatch", [(4, 2), (1, 8)])
def test_fused_prefill_matches_decode_rate(model, prompt, num_stages,
                                           microbatch):
    """prefill=True seeds the caches with the pipelined full-sequence pass;
    greedy tokens must match the decode-rate teacher-forced path."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=num_stages,
                           microbatch=microbatch, max_len=MAX_LEN)
    slow = dec.generate(prompt, max_new_tokens=9)
    fast = dec.generate(prompt, max_new_tokens=9, prefill=True)
    np.testing.assert_array_equal(slow, fast)


def test_prefill_single_new_token(model, prompt):
    """max_new_tokens=1 with prefill needs zero decode steps."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    ref = dec.generate(prompt, max_new_tokens=1)
    got = dec.generate(prompt, max_new_tokens=1, prefill=True)
    np.testing.assert_array_equal(ref, got)


def test_prefill_with_chunking_and_eos(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    ref = dec.generate(prompt, max_new_tokens=8)
    got = dec.generate(prompt, max_new_tokens=8, prefill=True,
                       token_chunk=2)
    np.testing.assert_array_equal(ref, got)
    eos = int(ref[0, 6])
    stopped = dec.generate(prompt, max_new_tokens=8, prefill=True,
                           token_chunk=2, eos_id=eos)
    gen = stopped[0, 5:]
    hits = np.where(gen == eos)[0]
    assert hits.size and (gen[hits[0]:] == eos).all()


def test_gqa_decode_matches_references(prompt):
    """GQA (kv_heads < num_heads): pipelined == incremental == recompute,
    and the engine's cache uses the narrow KV head count."""
    graph = gpt_tiny(seq_len=MAX_LEN, vocab=VOCAB, kv_heads=1)
    params = graph.init(jax.random.key(9))
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    assert dec.num_kv_heads == 1 and dec.num_heads == 2
    assert dec._cache_shape[3] == 1          # cache halved vs MHA
    got = dec.generate(prompt, max_new_tokens=8)
    want = incremental_greedy(graph, params, prompt, 5 + 8, MAX_LEN)
    np.testing.assert_array_equal(got, want)
    full = full_recompute_greedy(graph, params, prompt, 5 + 8)
    np.testing.assert_array_equal(got, full)
    fast = dec.generate(prompt, max_new_tokens=8, prefill=True)
    np.testing.assert_array_equal(got, fast)


def test_batch_beyond_one_pipeline_fill(model, prompt):
    """B > num_stages*microbatch runs in rounds; results match per-row."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=2,
                           max_len=MAX_LEN)
    got = dec.generate(prompt, max_new_tokens=6)       # B=8 > 4
    want = incremental_greedy(graph, params, prompt, 5 + 6, MAX_LEN)
    np.testing.assert_array_equal(got, want)


def test_multi_round_sampling_draws_independently(model):
    """Identical prompts in different rounds must not sample identical
    continuations (each round derives its own seed)."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=2,
                           max_len=MAX_LEN)
    same = np.full((8, 5), 3, np.int32)  # two rounds of four equal prompts
    out = dec.generate(same, 8, temperature=1.0, seed=0)
    assert not np.array_equal(out[:4], out[4:])


def test_int8_kv_cache(model, prompt):
    """kv_cache='int8': int8 rows + per-row scales, ~1/254 relative error;
    composes with prefill and GQA-free decode alike."""
    graph, params = model
    ref_dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                               max_len=MAX_LEN)
    q_dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                             max_len=MAX_LEN, kv_cache="int8")
    assert q_dec._init_state()[1]["k"].dtype == jnp.int8
    ref = ref_dec.generate(prompt, max_new_tokens=8)
    got = q_dec.generate(prompt, max_new_tokens=8)
    # tokens may differ where logits are within quant error; demand strong
    # agreement on this tiny model and exact prompt echo
    assert (got[:, :5] == prompt).all()
    agree = (got == ref).mean()
    assert agree > 0.9, (agree, got, ref)
    # deterministic + prefill path works
    np.testing.assert_array_equal(got, q_dec.generate(prompt, 8))
    pre = q_dec.generate(prompt, max_new_tokens=8, prefill=True)
    assert (pre == got).mean() > 0.9


def test_defer_generate_convenience(model, prompt):
    """Defer.generate wires the decoder into the flagship API."""
    import defer_tpu as dt
    graph, params = model
    defer = dt.Defer(config=dt.DeferConfig(microbatch=2))
    got = defer.generate(graph, params, prompt, 6, num_stages=4)
    want = incremental_greedy(graph, params, prompt, 5 + 6, MAX_LEN)
    np.testing.assert_array_equal(got, want)


def test_defer_score(model, prompt):
    """Defer.score: pipeline log-likelihood == direct single-program."""
    import defer_tpu as dt
    graph, params = model
    rng = np.random.default_rng(5)
    ids = rng.integers(0, VOCAB, size=(4, 10)).astype(np.int32)
    defer = dt.Defer(config=dt.DeferConfig(microbatch=2, chunk=4))
    lp, ppl = defer.score(graph, params, ids, num_stages=4)
    logits = np.asarray(graph.apply(params, jnp.asarray(ids)))
    ref_logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), -1)
    pick = jnp.take_along_axis(ref_logp[:, :-1],
                               jnp.asarray(ids[:, 1:, None]), -1)[..., 0]
    np.testing.assert_allclose(lp, np.asarray(pick.sum(-1)), rtol=1e-4)
    assert (ppl > 0).all() and np.allclose(ppl, np.exp(-lp / 9), rtol=1e-6)


def test_w8a16_weight_quant_decode(model, prompt):
    """int8 weight-only decoding (channel-wise scales, dequant fused in
    the stage branch): the buffer really is int8, generations agree
    strongly with the f32 engine, and reweight works under quant."""
    graph, params = model
    ref = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    q = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                         max_len=MAX_LEN, weight_dtype="int8")
    assert q._w["q"].dtype == jnp.int8
    # the weight stream is 1 byte/elem vs 4 (scales only matter for the
    # tiny 1-D leaves; on real geometries they are ~1/last_dim overhead)
    assert q._w["q"].nbytes == ref._w.nbytes // 4
    a = ref.generate(prompt, 8)
    b = q.generate(prompt, 8)
    assert (b[:, :5] == prompt).all()          # exact prompt echo
    agree = (a == b).mean()
    assert agree > 0.9, (agree, a, b)
    np.testing.assert_array_equal(b, q.generate(prompt, 8))  # deterministic
    # prefill path under quant weights
    pre = q.generate(prompt, 8, prefill=True)
    assert (pre == b).mean() > 0.9
    # reweight re-quantizes: scaled weights change the generation but the
    # engine stays compiled
    compiled = len(q._decode_fns) + len(q._prefill_fns)
    q.reweight(jax.tree.map(lambda x: x * 1.1, params))
    q.generate(prompt, 8)
    assert len(q._decode_fns) + len(q._prefill_fns) == compiled


def test_decoder_reweight_no_recompile(model, prompt):
    """Weights-only re-push on the decode engine: fresh params install
    into the live flat buffer, compiled decode programs are reused, and
    generations match the single-device oracle under the new weights."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    a = dec.generate(prompt, 6)
    compiled_before = len(dec._decode_fns) + len(dec._prefill_fns)

    params2 = jax.tree.map(lambda x: x * 1.1, params)
    dec.reweight(params2)
    b = dec.generate(prompt, 6)
    np.testing.assert_array_equal(
        b, incremental_greedy(graph, params2, prompt, 5 + 6, MAX_LEN))
    assert len(dec._decode_fns) + len(dec._prefill_fns) == compiled_before

    dec.reweight(params)  # originals restore the original generation
    np.testing.assert_array_equal(dec.generate(prompt, 6), a)

    bad = dict(params2)
    bad["lm_head"] = {"w": np.zeros((2, 2), np.float32),
                      "b": np.zeros((2,), np.float32)}
    with pytest.raises(ValueError, match="reweight"):
        dec.reweight(bad)
    # dtype drift with matching shapes must also be refused: the buffer
    # would otherwise blind-cast the values
    drift = dict(params)
    drift["lm_head"] = jax.tree.map(
        lambda a: np.asarray(a).astype(np.int32), params["lm_head"])
    with pytest.raises(ValueError, match="reweight"):
        dec.reweight(drift)


def test_defer_score_bucketed_short_sequence(model):
    """Scoring T=6 under a 24-token graph routes through a power-of-two
    bucketed pipeline (8 positions, not 24) with identical results."""
    import defer_tpu as dt
    graph, params = model
    rng = np.random.default_rng(9)
    ids = rng.integers(0, VOCAB, size=(4, 6)).astype(np.int32)
    defer = dt.Defer(config=dt.DeferConfig(microbatch=2, chunk=4))
    lp, ppl = defer.score(graph, params, ids, num_stages=4)
    # the cached pipeline really is the short-bucket one
    (g_ref, p_ref, pipe), = [v for k, v in defer._score_cache.items()]
    assert g_ref is graph and p_ref is params
    assert pipe.in_spec.shape[0] == 8  # next pow2 >= 6
    # identical log-likelihoods vs the full-length direct computation
    logits = np.asarray(graph.apply(params, jnp.asarray(
        np.pad(ids, ((0, 0), (0, MAX_LEN - 6))))))[:, :6]
    ref_logp = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), -1)
    pick = jnp.take_along_axis(ref_logp[:, :-1],
                               jnp.asarray(ids[:, 1:, None]), -1)[..., 0]
    np.testing.assert_allclose(lp, np.asarray(pick.sum(-1)), rtol=1e-4)
    # second call with the same (graph, params, T-bucket) reuses the pipe
    defer.score(graph, params, ids, num_stages=4)
    assert len(defer._score_cache) == 1


@pytest.mark.slow
def test_defer_score_bucket_speedup():
    """The bucketed path must actually be cheaper: steady-state scoring of
    short sequences beats the full-length pipeline by >=4x (VERDICT r4 #8
    'done' bar).  Needs a compute-dominated config (T=256, d=128) so the
    per-dispatch overhead doesn't mask the work ratio; timed on compiled,
    warmed pipelines, min over reps."""
    import time
    import defer_tpu as dt
    from defer_tpu.models.gpt import gpt
    graph = gpt(4, 128, 4, seq_len=256, vocab=VOCAB, name="gpt_score_perf")
    params = graph.init(jax.random.key(2))
    rng = np.random.default_rng(11)
    ids = rng.integers(0, VOCAB, size=(4, 10)).astype(np.int32)
    defer = dt.Defer(config=dt.DeferConfig(microbatch=2, chunk=4))

    def steady(fn):
        fn()  # compile/warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    short = steady(lambda: defer.score(graph, params, ids, num_stages=4))
    full_ids = np.zeros((4, 256), np.int32)
    full_ids[:, :10] = ids  # the old behavior: pad to the graph's length
    full = steady(lambda: defer.score(graph, params, full_ids,
                                      num_stages=4))
    assert full / short >= 4, (short, full)


def test_defer_generate_caches_decoder(model, prompt):
    """Repeated Defer.generate reuses one PipelinedDecoder (ADVICE r4):
    rebuilding repacked weights + re-jitted the decode program per call."""
    import defer_tpu as dt
    graph, params = model
    defer = dt.Defer(config=dt.DeferConfig(microbatch=2))
    a = defer.generate(graph, params, prompt, 4, num_stages=4)
    dec1 = next(iter(defer._decoder_cache.values()))[2]
    b = defer.generate(graph, params, prompt, 4, num_stages=4)
    dec2 = next(iter(defer._decoder_cache.values()))[2]
    assert dec1 is dec2 and len(defer._decoder_cache) == 1
    np.testing.assert_array_equal(a, b)
    # different kv_cache => different engine, cache grows
    defer.generate(graph, params, prompt, 4, num_stages=4, kv_cache="int8")
    assert len(defer._decoder_cache) == 2


def test_gqa_int8_prefill_sampling_compose(prompt):
    """All decoder features at once: GQA + int8 cache + fused prefill +
    top-k sampling + chunking + EOS, generating to the max_len boundary."""
    graph = gpt_tiny(seq_len=MAX_LEN, vocab=VOCAB, kv_heads=1)
    params = graph.init(jax.random.key(11))
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN, kv_cache="int8")
    new = MAX_LEN - 5  # generate right up to the positional-table edge
    a = dec.generate(prompt, new, prefill=True, temperature=0.7,
                     top_k=7, seed=3, token_chunk=4)
    b = dec.generate(prompt, new, prefill=True, temperature=0.7,
                     top_k=7, seed=3)  # single dispatch
    np.testing.assert_array_equal(a, b)  # chunking-invariant end to end
    assert a.shape == (8, MAX_LEN)
    assert (a[:, :5] == prompt).all()
    assert ((a >= 0) & (a < VOCAB)).all()
    eos = int(a[0, 7])
    c = dec.generate(prompt, new, prefill=True, temperature=0.7,
                     top_k=7, seed=3, token_chunk=4, eos_id=eos)
    gen = c[0, 5:]
    hits = np.where(gen == eos)[0]
    assert hits.size and (gen[hits[0]:] == eos).all()


def reference_beam(graph, params, prompt, max_new, beam, max_len):
    """Single-device beam search from the same decode ops + expansion math
    (flat top-k of beam*V cumulative log-probs, duplicate-masked first
    expansion, cache re-parenting before each append)."""
    nodes = graph.nodes
    blocks = [nm for nm in graph.topo_order if nm.startswith("block_")]
    op0 = nodes[blocks[0]].op
    d = nodes[blocks[0]].out_spec.shape[-1]
    vocab = nodes["lm_head"].out_spec.shape[-1]
    b, plen = prompt.shape
    t_tok = plen + max_new
    outs = []
    for s in range(b):
        seqs = np.tile(prompt[s], (beam, 1)).astype(np.int64)
        shape = (beam, op0.kv_heads, max_len + 1, d // op0.num_heads)
        kc = {nm: jnp.zeros(shape) for nm in blocks}
        vc = {nm: jnp.zeros(shape) for nm in blocks}
        cum = jnp.zeros(beam)
        for p in range(t_tok - 1):
            tok = jnp.asarray(seqs[:, p], jnp.int32)
            x = nodes["embeddings"].op.embed_at(params["embeddings"],
                                                tok, p)
            for nm in blocks:
                x, kc[nm], vc[nm] = nodes[nm].op.decode(
                    params[nm], x, kc[nm], vc[nm], p)
            if p < plen - 1:
                continue  # forced prompt token; no expansion
            h = nodes["final_ln"].op.apply(params["final_ln"], x)
            logits = nodes["lm_head"].op.apply(
                params["lm_head"], h).astype(jnp.float32)
            sc = cum[:, None] + jax.nn.log_softmax(logits, -1)
            if p == plen - 1:
                sc = sc.at[1:].set(-jnp.inf)
            best, idx = jax.lax.top_k(sc.reshape(1, beam * vocab), beam)
            parent = np.asarray(idx[0] // vocab)
            new_tok = np.asarray(idx[0] % vocab, np.int64)
            cum = best[0]
            seqs = np.concatenate([seqs[parent],
                                   new_tok[:, None]], axis=1)
            kc = {nm: jnp.take(kc[nm], jnp.asarray(parent), axis=0)
                  for nm in blocks}
            vc = {nm: jnp.take(vc[nm], jnp.asarray(parent), axis=0)
                  for nm in blocks}
        outs.append(seqs[int(np.argmax(np.asarray(cum)))])
    return np.stack(outs)


@pytest.mark.parametrize("num_stages,microbatch,beam", [(4, 4, 2), (2, 6, 3),
                                                        (1, 4, 4)])
def test_pipelined_beam_matches_reference(model, prompt, num_stages,
                                          microbatch, beam):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=num_stages,
                           microbatch=microbatch, max_len=MAX_LEN,
                           beam_width=beam)
    nspg = microbatch // beam
    b = min(8, num_stages * nspg)
    b -= b % nspg
    got = dec.generate(prompt[:b], max_new_tokens=8)
    want = reference_beam(graph, params, prompt[:b], 8, beam, MAX_LEN)
    np.testing.assert_array_equal(got, want)


def test_beam_with_chunked_dispatch(model, prompt):
    """Chunk-overshoot steps must be true bubbles: with token_chunk the
    final dispatch overruns num_steps, and an un-guarded extra expansion
    would corrupt the beam ledger before the host picks the best beam."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN, beam_width=2)
    whole = dec.generate(prompt[:4], max_new_tokens=7)
    chunked = dec.generate(prompt[:4], max_new_tokens=7, token_chunk=1)
    np.testing.assert_array_equal(whole, chunked)
    want = reference_beam(graph, params, prompt[:4], 7, 2, MAX_LEN)
    np.testing.assert_array_equal(whole, want)


def test_beam_one_equals_greedy(model, prompt):
    graph, params = model
    greedy = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                              max_len=MAX_LEN)
    beam1 = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                             max_len=MAX_LEN, beam_width=1)
    np.testing.assert_array_equal(greedy.generate(prompt, 6),
                                  beam1.generate(prompt, 6))


def test_token_streaming_callback(model, prompt):
    """on_tokens delivers contiguous, non-overlapping spans that concat to
    exactly the generated region — with chunking and with prefill."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=4, microbatch=2,
                           max_len=MAX_LEN)
    for kw in (dict(token_chunk=2), dict(token_chunk=3, prefill=True)):
        spans = []
        out = dec.generate(prompt, 9, on_tokens=lambda lo, hi, t, rows:
                           spans.append((lo, hi, t, rows)), **kw)
        los = [s[0] for s in spans]
        his = [s[1] for s in spans]
        assert los[0] == 5 and his[-1] == 14
        assert all(h == l for h, l in zip(his[:-1], los[1:]))  # contiguous
        assert all(s[3] == (0, 8) for s in spans)
        streamed = np.concatenate([s[2] for s in spans], axis=1)
        np.testing.assert_array_equal(streamed, out[:, 5:])


def test_streaming_multi_round(model, prompt):
    """B beyond one pipeline fill: spans arrive per round with the round's
    row range, and together cover every sequence."""
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=2,
                           max_len=MAX_LEN)  # capacity 4 < B=8
    spans = []
    out = dec.generate(prompt, 6, token_chunk=2,
                       on_tokens=lambda lo, hi, t, rows:
                       spans.append((lo, hi, t, rows)))
    row_ranges = {s[3] for s in spans}
    assert row_ranges == {(0, 4), (4, 8)}
    for r0, r1 in sorted(row_ranges):
        streamed = np.concatenate(
            [s[2] for s in spans if s[3] == (r0, r1)], axis=1)
        np.testing.assert_array_equal(streamed, out[r0:r1, 5:])


def test_streaming_with_eos(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    ref = dec.generate(prompt, 10)
    eos = int(ref[0, 6])
    spans = []
    out = dec.generate(prompt, 10, eos_id=eos, token_chunk=2,
                       on_tokens=lambda lo, hi, t, rows:
                       spans.append((lo, hi)))
    assert spans and spans[0][0] == 5
    gen = out[0, 5:]
    hits = np.where(gen == eos)[0]
    assert hits.size and (gen[hits[0]:] == eos).all()


def test_beam_with_int8_cache(model, prompt):
    """Beam re-parenting gathers the int8 cache AND its scale entries."""
    graph, params = model
    exact = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                             max_len=MAX_LEN, beam_width=2)
    quant = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                             max_len=MAX_LEN, beam_width=2,
                             kv_cache="int8")
    a = exact.generate(prompt[:4], 7)
    b = quant.generate(prompt[:4], 7)
    assert a.shape == b.shape and (b[:, :5] == prompt[:4]).all()
    assert (a == b).mean() > 0.85, (a, b)
    np.testing.assert_array_equal(b, quant.generate(prompt[:4], 7))


def test_beam_validation(model, prompt):
    graph, params = model
    with pytest.raises(ValueError, match="divide"):
        PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                         max_len=MAX_LEN, beam_width=3)
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN, beam_width=2)
    with pytest.raises(ValueError, match="beam search"):
        dec.generate(prompt[:4], 4, prefill=True)
    with pytest.raises(ValueError, match="beam search"):
        dec.generate(prompt[:4], 4, temperature=0.5)


def test_quantize_row_roundtrip():
    from defer_tpu.models.gpt import CausalTransformerBlock
    rng = np.random.default_rng(0)
    row = jnp.asarray(rng.standard_normal((3, 2, 7, 16)) * 5)
    q, s = CausalTransformerBlock.quantize_row(row)
    assert q.dtype == jnp.int8 and s.shape == (3, 2, 7)
    dq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    err = np.abs(dq - np.asarray(row))
    bound = np.abs(np.asarray(row)).max(-1) / 127.0 * 0.5 + 1e-7
    assert (err <= bound[..., None] + 1e-5).all()


def test_gqa_param_shapes():
    from defer_tpu.models.gpt import CausalTransformerBlock
    from defer_tpu.graph.ir import ShapeSpec
    blk = CausalTransformerBlock(4, num_kv_heads=2)
    p = blk.init(jax.random.key(0), (ShapeSpec((6, 32)),))
    assert p["qkv"]["w"].shape == (32, 32 + 2 * 2 * 8)  # d + 2*kv*hd
    # GQA tensor parallelism (added r5): each rank holds whole query
    # groups — nh/tp query cols + kv/tp KV cols each for K and V
    shard = blk.tp_shard(p, 2, 0)
    assert shard["qkv"]["w"].shape == (32, 16 + 2 * 8)


def test_repeat_generate_reuses_compiled_program(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    a = dec.generate(prompt, max_new_tokens=4)
    b = dec.generate(prompt, max_new_tokens=4)
    np.testing.assert_array_equal(a, b)
    assert len(dec._decode_fns) == 1


def test_validation_errors(model, prompt):
    graph, params = model
    dec = PipelinedDecoder(graph, params, num_stages=2, microbatch=4,
                           max_len=MAX_LEN)
    with pytest.raises(ValueError, match="multiple of microbatch"):
        dec.generate(prompt[:3], max_new_tokens=2)
    with pytest.raises(ValueError, match="exceeds"):
        dec.generate(prompt, max_new_tokens=MAX_LEN)
    with pytest.raises(ValueError, match="at least one token"):
        dec.generate(np.zeros((8, 0), np.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_len"):
        PipelinedDecoder(graph, params, num_stages=2, microbatch=1,
                         max_len=MAX_LEN + 1)
    with pytest.raises(ValueError, match="attn_impl"):
        blk = CausalTransformerBlock(2, attn_impl="Flash")
        blk._attend(jnp.zeros((1, 2, 4, 16)), jnp.zeros((1, 2, 4, 16)),
                    jnp.zeros((1, 2, 4, 16)))


def test_split_blocks():
    assert _split_blocks(4, 4) == [[0], [1], [2], [3]]
    assert _split_blocks(12, 4) == [[0, 1, 2], [3, 4, 5], [6, 7, 8],
                                    [9, 10, 11]]
    assert _split_blocks(5, 2) == [[0, 1], [2, 3, 4]]
    with pytest.raises(ValueError):
        _split_blocks(2, 4)


def test_causal_block_full_vs_decode(model):
    """Full-sequence causal apply == stepwise decode on the same tokens."""
    graph, params = model
    blk_name = "block_0"
    op: CausalTransformerBlock = graph.nodes[blk_name].op
    p = params[blk_name]
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 6, 32)), jnp.float32)
    full = np.asarray(op.apply(p, x))
    d = x.shape[-1]
    kc = jnp.zeros((2, op.num_heads, 8, d // op.num_heads))
    vc = jnp.zeros((2, op.num_heads, 8, d // op.num_heads))
    for t in range(6):
        y, kc, vc = op.decode(p, x[:, t], kc, vc, t)
        np.testing.assert_allclose(np.asarray(y), full[:, t],
                                   rtol=2e-5, atol=2e-5)


def test_gpt_full_sequence_pipeline(model):
    """The causal graph rides the ordinary inference pipeline (scoring)."""
    from defer_tpu import SpmdPipeline, partition, pipeline_mesh
    graph, params = model
    cuts = gpt_stage_cuts(4, 4)
    stages = partition(graph, cuts)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=2, chunk=4)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, VOCAB, size=(3, 2, MAX_LEN)).astype(np.float32)
    got = pipe.run(ids)
    want = np.stack([
        np.asarray(graph.apply(params, jnp.asarray(m, jnp.int32)))
        for m in ids])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
