"""Dispatcher API tests: queue-in/queue-out streaming service (capability
parity with reference run_defer, src/dispatcher.py:107) and generator
streaming."""

import queue

import jax
import numpy as np

from defer_tpu import Defer, DeferConfig, END_OF_STREAM
from defer_tpu.models import resnet_tiny


def _ref(g, params, xs):
    fn = jax.jit(g.apply)
    return np.stack([np.asarray(fn(params, x), np.float32) for x in xs])


def test_run_batch_api():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    inputs = np.asarray(jax.random.normal(jax.random.key(1), (5, 1, 32, 32, 3)))
    out = defer.run(g, params, inputs, num_stages=4)
    np.testing.assert_allclose(out, _ref(g, params, inputs),
                               rtol=2e-4, atol=2e-4)


def test_stream_generator():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    inputs = np.asarray(jax.random.normal(jax.random.key(2), (6, 1, 32, 32, 3)))
    outs = list(defer.stream(g, params, iter(inputs), num_stages=2))
    assert len(outs) == 6
    got = np.stack([np.asarray(o, np.float32) for o in outs])
    np.testing.assert_allclose(got, _ref(g, params, inputs),
                               rtol=2e-4, atol=2e-4)


def test_run_defer_queue_service():
    """The reference harness pattern (test/test.py:39-51): spawn run_defer,
    feed an input queue, drain an output queue."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    in_q, out_q = queue.Queue(maxsize=10), queue.Queue()
    handle = defer.run_defer(g, params, ["add_1"], in_q, out_q)

    inputs = np.asarray(jax.random.normal(jax.random.key(3), (7, 1, 32, 32, 3)))
    for x in inputs:
        in_q.put(x)
    in_q.put(END_OF_STREAM)
    handle.join(timeout=120)

    outs = []
    while not out_q.empty():
        outs.append(out_q.get())
    assert len(outs) == 7
    got = np.stack(outs)
    np.testing.assert_allclose(got, _ref(g, params, inputs),
                               rtol=2e-4, atol=2e-4)
    assert handle.metrics.inferences == 7


def test_run_defer_mpmd_mode():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(mode="mpmd"))
    in_q, out_q = queue.Queue(), queue.Queue()
    handle = defer.run_defer(g, params, ["add_1"], in_q, out_q)
    inputs = np.asarray(jax.random.normal(jax.random.key(4), (3, 1, 32, 32, 3)))
    for x in inputs:
        in_q.put(x)
    in_q.put(END_OF_STREAM)
    handle.join(timeout=120)
    outs = [out_q.get_nowait() for _ in range(3)]
    got = np.stack(outs)
    np.testing.assert_allclose(got, _ref(g, params, inputs),
                               rtol=2e-4, atol=2e-4)


def test_run_defer_error_propagates():
    """A bad input must not silently kill the serve thread (consumers would
    block forever); join() re-raises and the output queue gets a sentinel."""
    import pytest

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2))
    in_q, out_q = queue.Queue(), queue.Queue()
    handle = defer.run_defer(g, params, ["add_1"], in_q, out_q)
    in_q.put(np.zeros((1, 8, 8, 3), np.float32))  # wrong spatial shape
    with pytest.raises(RuntimeError, match="dispatcher thread failed"):
        handle.join(timeout=120)
    assert out_q.get(timeout=10) is END_OF_STREAM
