"""Multi-host helpers (single-host degradation paths).

Real DCN behavior needs multiple hosts; these tests pin the single-host
contracts: initialize() is a safe no-op, the global mesh covers all local
devices with the documented axis order, and per-host batch sharding
validates divisibility.
"""

import pytest

import jax

from defer_tpu import (initialize, multihost_pipeline_mesh,
                       process_local_batch)


def test_initialize_single_host_noop():
    initialize()  # must not raise or hang on one host
    assert jax.process_count() == 1


def test_multihost_mesh_axes():
    mesh = multihost_pipeline_mesh(4, data_parallel=2)
    assert mesh.shape == {"data": 2, "stage": 4}
    mesh3 = multihost_pipeline_mesh(2, data_parallel=2, tensor_parallel=2)
    assert mesh3.shape == {"data": 2, "stage": 2, "model": 2}


def test_multihost_mesh_too_big_raises():
    with pytest.raises(ValueError, match="available"):
        multihost_pipeline_mesh(64, data_parallel=64)


def test_process_local_batch():
    assert process_local_batch(32) == 32  # one host owns everything
