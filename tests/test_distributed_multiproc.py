"""Simulated multi-host cluster: two real OS processes + a coordinator.

The reference validates multi-machine behavior only in an external network
emulator (SURVEY.md §4); here two local processes form an actual
``jax.distributed`` cluster over localhost (CPU backend, 4 virtual devices
per process) and assert the things ``tests/test_distributed.py`` can only
assert vacuously on one process:

* ``initialize`` with explicit coordinator args forms the cluster
  (process_count == 2, 8 global devices);
* ``multihost_pipeline_mesh`` spans both hosts and lays the stage axis out
  host-major — consecutive stages stay on one host except at the single
  host-boundary hop (the DCN-crossing claim of
  parallel/distributed.py:60-74);
* a ``psum`` over the global mesh actually crosses the process boundary.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import numpy as np

import jax
from defer_tpu.parallel.distributed import (initialize,
                                            multihost_pipeline_mesh,
                                            process_local_batch)
from defer_tpu.parallel.mesh import STAGE_AXIS

pid = int(sys.argv[1])
initialize(coordinator_address="127.0.0.1:%PORT%",
           num_processes=2, process_id=pid)

assert jax.process_count() == 2, jax.process_count()
devs = jax.devices()
assert len(devs) == 8, len(devs)

mesh = multihost_pipeline_mesh(8)
stage_devs = list(mesh.devices.flatten())
# host-major stage layout: stages 0-3 on process 0, stages 4-7 on
# process 1 -> exactly ONE host-boundary hop in the stage chain
owners = [d.process_index for d in stage_devs]
assert owners == sorted(owners), owners
assert sum(1 for a, b in zip(owners, owners[1:]) if a != b) == 1, owners

# a collective over the global mesh crosses the process boundary
from jax.sharding import NamedSharding, PartitionSpec as P
x = jax.device_put(
    np.arange(8, dtype=np.float32),
    NamedSharding(mesh, P(STAGE_AXIS)))
total = jax.jit(
    jax.shard_map(lambda a: jax.lax.psum(a, STAGE_AXIS), mesh=mesh,
                  in_specs=P(STAGE_AXIS), out_specs=P()))(x)
np.testing.assert_allclose(np.asarray(total), [28.0])

assert process_local_batch(16) == 8
print(f"worker {pid} OK", flush=True)
"""


@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    import socket
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]
    srv.close()

    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("%PORT%", str(port)))

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",  # never touch the TPU tunnel
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
    })
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {i} rc={rc}\n{err[-3000:]}"
        assert f"worker {i} OK" in out
