"""Multi-client serve_endpoint (VERDICT r4 #6): N clients share one
compiled pipeline; each receives exactly its own outputs in its own send
order, and a client death doesn't disturb the others or the server.

Goes beyond the reference, which is single-connection everywhere
(``listen(1)``, reference src/node.py:84-85).
"""

import socket
import threading

import numpy as np

import jax

from defer_tpu import Defer, DeferConfig
from defer_tpu.models import resnet_tiny
from defer_tpu.transport.framed import TensorClient, send_frame


def _model():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def test_two_concurrent_clients_each_get_their_own_results():
    g, params = _model()
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    address, thread = defer.serve_endpoint(g, params, num_stages=4,
                                           max_clients=2)
    rng = np.random.default_rng(1)
    # distinct input sets so cross-delivery would be caught
    xs = {k: [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
              for _ in range(7)] for k in ("a", "b")}
    outs = {}

    def go(k):
        c = TensorClient(*address)
        outs[k] = c.infer_stream(xs[k])
        c.close()

    ts = [threading.Thread(target=go, args=(k,)) for k in xs]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    thread.join(timeout=60)
    assert not thread.is_alive()
    assert thread.errors == []

    fwd = jax.jit(g.apply)
    for k in xs:
        assert len(outs[k]) == 7
        for x, y in zip(xs[k], outs[k]):
            np.testing.assert_allclose(y, np.asarray(fwd(params, x)),
                                       rtol=2e-4, atol=2e-4)


def test_operator_stop_terminates_undersubscribed_endpoint():
    """An endpoint expecting 4 clients that only ever saw 1 must still be
    stoppable: thread.stop() drains in-flight rows and the serve thread
    exits (it used to pin the socket + pipeline forever)."""
    g, params = _model()
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    address, thread = defer.serve_endpoint(g, params, num_stages=4,
                                           max_clients=4)
    rng = np.random.default_rng(3)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(3)]
    c = TensorClient(*address)
    outs = c.infer_stream(xs)
    c.close()
    assert len(outs) == 3
    assert thread.is_alive()  # still waiting for 3 more clients
    thread.stop()
    thread.join(timeout=60)
    assert not thread.is_alive()


def test_live_reweight_between_clients():
    """thread.reweight swaps serving weights with no recompile: a second
    client sees the new model's outputs over the same endpoint."""
    g, params = _model()
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    address, thread = defer.serve_endpoint(g, params, num_stages=4,
                                           max_clients=2)
    rng = np.random.default_rng(4)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(3)]
    c1 = TensorClient(*address)
    out1 = c1.infer_stream(xs)
    c1.close()
    params2 = jax.tree.map(lambda a: a * 1.5, params)
    thread.reweight(params2)
    c2 = TensorClient(*address)
    out2 = c2.infer_stream(xs)
    c2.close()
    thread.join(timeout=60)
    fwd = jax.jit(g.apply)
    for x, y1, y2 in zip(xs, out1, out2):
        np.testing.assert_allclose(y1, np.asarray(fwd(params, x)),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(y2, np.asarray(fwd(params2, x)),
                                   rtol=2e-4, atol=2e-4)


def test_client_death_then_reconnect():
    """A client that dies mid-stream (no END) is discarded; a fresh client
    connecting afterwards is served normally over the same pipeline."""
    g, params = _model()
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    address, thread = defer.serve_endpoint(g, params, num_stages=4,
                                           max_clients=2)
    # client 1: push two samples then die without END
    raw = socket.create_connection(address)
    x = np.zeros((1, 32, 32, 3), np.float32)
    send_frame(raw, x)
    send_frame(raw, x)
    raw.close()

    # client 2: full clean stream
    rng = np.random.default_rng(2)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    c = TensorClient(*address)
    outs = c.infer_stream(xs)
    c.close()
    thread.join(timeout=120)
    assert not thread.is_alive()

    assert len(outs) == 5
    fwd = jax.jit(g.apply)
    for xi, y in zip(xs, outs):
        np.testing.assert_allclose(y, np.asarray(fwd(params, xi)),
                                   rtol=2e-4, atol=2e-4)
