"""Expert parallelism: all_to_all-dispatched MoE == dense single-device MoE.

Same invariant family as the partitioner and TP tests (sharded execution
reproduces the unsharded forward) applied to the expert axis, with switch
capacity semantics: exact equality while no expert overflows capacity.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu.graph.ir import GraphBuilder
from defer_tpu.graph.ops import MoE
from defer_tpu.parallel.expert import (expert_parallel_fn,
                                       expert_parallel_mesh,
                                       shard_moe_params)


def make_moe(e=8, d=16, h=32):
    op = MoE(num_experts=e, hidden=h)
    b = GraphBuilder("moe")
    x = b.input((4, d))
    b.add(op, x, name="moe")
    g = b.build()
    params = g.init(jax.random.key(0))["moe"]
    return op, params


@pytest.mark.parametrize("ep", [2, 4, 8])
def test_ep_matches_dense(ep):
    op, params = make_moe()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4, 16)).astype(np.float32)

    ref = op.apply(params, jnp.asarray(x))

    mesh = expert_parallel_mesh(ep)
    stk = shard_moe_params(op, params, ep, mesh=mesh)
    # generous capacity: no token dropped -> exact parity
    out = expert_parallel_fn(op, mesh, capacity_factor=float(ep))(
        stk, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ep_capacity_drops_fall_back_to_residual():
    """With capacity 1 per device, overflow tokens keep only the residual
    path (switch-style token dropping), never garbage."""
    op, params = make_moe(e=2)
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 4, 16)).astype(np.float32)

    mesh = expert_parallel_mesh(2)
    stk = shard_moe_params(op, params, 2, mesh=mesh)
    out = np.asarray(expert_parallel_fn(
        op, mesh, tokens_per_device=2, capacity_factor=1.0)(
            stk, jnp.asarray(x)))

    ref = np.asarray(op.apply(params, jnp.asarray(x)))
    # every token is either the exact dense result or the pure residual
    matches_ref = np.isclose(out, ref, atol=1e-5).all(axis=-1)
    matches_res = np.isclose(out, x, atol=1e-5).all(axis=-1)
    assert (matches_ref | matches_res).all()
    assert matches_res.any()  # capacity 1 must actually drop something


def test_moe_params_shard_disjoint():
    op, params = make_moe(e=8)
    s0 = shard_moe_params(op, params, 4)
    assert s0["fc1"]["w"].shape == (4, 2, 16, 32)
    np.testing.assert_array_equal(np.asarray(s0["gate"][0]),
                                  np.asarray(s0["gate"][1]))
