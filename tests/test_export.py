"""Stage artifact export/load: StableHLO + weights roundtrip.

Invariant: a stage reloaded from its serialized artifact (in a codebase
that needs no model definition) computes exactly what the live stage
computes — the reference's ship-JSON-then-set_weights contract
(src/dispatcher.py:44-65 / src/node.py:31-34) without Keras or sockets.
"""

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import bert_tiny, resnet_tiny
from defer_tpu.utils.export import export_pipeline, export_stage, load_stage


def test_stage_roundtrip_exact(tmp_path):
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=4)
    s = stages[1]
    path = str(tmp_path / "s1.zip")
    export_stage(s, params, path, batch=2)

    fn, manifest = load_stage(path)
    assert manifest["index"] == 1
    assert tuple(manifest["in_shape"]) == s.in_spec.shape
    x = np.random.default_rng(0).normal(
        size=(2,) + s.in_spec.shape).astype(np.float32)
    want = s.fn(s.select_params(params), x)
    got = fn(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_pipeline_export_chains_to_full_model(tmp_path):
    """Relaying an input through all reloaded stage artifacts reproduces
    the full model — the partition-equivalence invariant over the wire
    format itself."""
    g = bert_tiny()
    params = g.init(jax.random.key(1))
    stages = partition(g, num_stages=2)
    paths = export_pipeline(stages, params, str(tmp_path), batch=1)
    assert len(paths) == 2

    ids = (np.arange(16).reshape(1, 16) % 100).astype(np.int32)
    ref = np.asarray(g.apply(params, ids))
    x = ids
    for p in paths:
        fn, _ = load_stage(p)
        x = np.asarray(fn(x))
    np.testing.assert_allclose(x, ref, rtol=1e-5, atol=1e-5)


def test_load_rejects_non_artifact(tmp_path):
    import zipfile
    bad = str(tmp_path / "bad.zip")
    with zipfile.ZipFile(bad, "w") as z:
        z.writestr("manifest.json", "{}")
    with pytest.raises(ValueError, match="not a defer_tpu stage"):
        load_stage(bad)
