"""Failure detection: health checks, error propagation, watchdog.

The reference's only failure behavior is a forever-hang (SURVEY.md §5: no
retry, no health check; a dead node stalls the chain).  These tests pin the
opposite contract: failures surface as errors, readers are unblocked, and a
deployment can be probed before serving.
"""

import queue
import time

import numpy as np
import pytest

import jax

from defer_tpu import Defer, DeferConfig, END_OF_STREAM
from defer_tpu.models import resnet_tiny


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def test_health_check_ok(tiny):
    g, p = tiny
    rep = Defer(config=DeferConfig(microbatch=1, chunk=2)).health_check(
        g, p, num_stages=4)
    assert rep["ok"] and rep["stages"] == 4
    assert rep["mesh"] == {"data": 1, "stage": 4}
    assert rep["error"] is None


def test_health_check_reports_failure(tiny):
    g, _ = tiny
    # missing parameters: every stage program fails at trace time — the
    # "bad deployment caught before serving" case
    rep = Defer(config=DeferConfig(microbatch=1, chunk=2)).health_check(
        g, {}, num_stages=1)
    assert not rep["ok"]
    assert rep["error"] is not None


def test_run_defer_propagates_stage_error(tiny):
    g, p = tiny
    in_q, out_q = queue.Queue(), queue.Queue()
    h = Defer(config=DeferConfig(microbatch=1, chunk=2)).run_defer(
        g, p, None, in_q, out_q, num_stages=2)
    # wrong input shape: the dispatch raises inside the serve thread
    in_q.put(np.zeros((1, 7), np.float32))
    # reader is unblocked by the sentinel instead of hanging forever
    assert out_q.get(timeout=120) is END_OF_STREAM
    assert not h.healthy
    with pytest.raises(RuntimeError, match="dispatcher thread failed"):
        h.join(timeout=60)


def test_watchdog_declares_hung_dispatch(tiny, monkeypatch):
    g, p = tiny
    # detection-only mode (max_recoveries=0): first fire is fatal
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2,
                                     watchdog_s=0.5, max_recoveries=0))
    in_q, out_q = queue.Queue(), queue.Queue()
    h = defer.run_defer(g, p, None, in_q, out_q, num_stages=2)
    # simulate a wedged device dispatch (e.g. a dead TPU tunnel) AFTER the
    # compile warmup: the serve thread reports busy and never finishes
    h._dispatches = 1
    h._busy_since = time.monotonic() - 10.0
    assert out_q.get(timeout=30) is END_OF_STREAM
    assert isinstance(h.error, TimeoutError)
    assert not h.healthy
    h.stop()


def test_failure_detection_defaults_on():
    """r1 shipped watchdog_s=None — the reference's forever-hang as the
    default config.  Pin the new contract: detection on out of the box."""
    cfg = DeferConfig()
    assert cfg.watchdog_s == 60.0
    assert cfg.preflight is True
    assert cfg.max_recoveries == 1  # recovery, not just detection (r5)


def test_watchdog_recovery_replays_unemitted(tiny):
    """VERDICT r4 #7: poison a dispatch mid-stream; the watchdog rebuilds
    the pipeline (fresh jit, same weights), replays the fed-but-unemitted
    microbatches from the resubmit log, and the output queue completes
    with no gaps — in order, matching the single-program oracle."""
    import threading

    g, p = tiny
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2, watchdog_s=0.5,
                                     gather_timeout_s=0.01))
    in_q, out_q = queue.Queue(), queue.Queue()
    h = defer.run_defer(g, p, None, in_q, out_q, num_stages=2)

    # poison the FIRST pipeline instance: its 3rd push (warmup is #1)
    # wedges forever — the simulated dead-device dispatch
    first_pipe = h.pipeline
    real_push = first_pipe.push
    wedge = threading.Event()
    calls = {"n": 0}

    def poisoned(xs, n_real=None, **kw):
        calls["n"] += 1
        if calls["n"] == 3:
            wedge.wait()  # never set: this generation is stuck for good
        return real_push(xs, n_real=n_real, **kw)

    first_pipe.push = poisoned

    rng = np.random.default_rng(7)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(8)]
    for x in xs:
        in_q.put(x)
    in_q.put(END_OF_STREAM)

    outs = []
    while len(outs) < 8:
        o = out_q.get(timeout=180)
        assert o is not END_OF_STREAM, \
            f"stream aborted after {len(outs)} outputs (error: {h.error!r})"
        outs.append(o)
    assert h.healthy
    assert h.recoveries == 1
    assert h.pipeline is not first_pipe  # fresh engine, same weights
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):  # no gaps, original feed order
        np.testing.assert_allclose(y, np.asarray(fwd(p, x)),
                                   rtol=2e-4, atol=2e-4)
    h.stop()
    wedge.set()  # let the abandoned generation's thread exit


def test_watchdog_recovery_after_end_consumed(tiny):
    """A wedge in the final-drain dispatch — AFTER the caller's
    END_OF_STREAM was consumed — must still recover: the new generation
    must not wait for a second END (none is coming); it replays, flushes,
    and completes the stream."""
    import threading

    g, p = tiny
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2, watchdog_s=0.5,
                                     gather_timeout_s=0.01))
    in_q, out_q = queue.Queue(), queue.Queue()
    h = defer.run_defer(g, p, None, in_q, out_q, num_stages=2)

    first_pipe = h.pipeline
    real_push = first_pipe.push
    wedge = threading.Event()
    calls = {"n": 0}

    def poisoned(xs, n_real=None, **kw):
        calls["n"] += 1
        # warmup=1, two input chunks=2..3, flush pushes start at 4
        if calls["n"] == 4:
            wedge.wait()
        return real_push(xs, n_real=n_real, **kw)

    first_pipe.push = poisoned

    rng = np.random.default_rng(13)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(4)]
    for x in xs:
        in_q.put(x)
    in_q.put(END_OF_STREAM)

    outs = []
    while len(outs) < 4:
        o = out_q.get(timeout=180)
        assert o is not END_OF_STREAM, \
            f"aborted after {len(outs)} (error: {h.error!r})"
        outs.append(o)
    assert h.healthy and h.recoveries == 1
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(y, np.asarray(fwd(p, x)),
                                   rtol=2e-4, atol=2e-4)
    h.stop()
    wedge.set()


def test_join_raises_immediately_when_error_set():
    """join() must re-raise a recorded error even while the serve thread is
    permanently wedged in a dead dispatch (it polls, never blocks forever)."""
    import threading
    from defer_tpu.runtime.dispatcher import DeferHandle

    release = threading.Event()
    th = threading.Thread(target=release.wait, daemon=True)
    th.start()
    h = DeferHandle(th, None, threading.Event())
    h.error = TimeoutError("deployment declared dead")
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="dispatcher thread failed"):
        h.join()  # unbounded join would hang here before the fix
    assert time.monotonic() - t0 < 5
    release.set()


def test_preflight_surfaces_compile_failure_without_input(tiny):
    """With preflight on, a deployment that cannot compile reports its error
    and unblocks readers before any input is ever enqueued."""
    g, p = tiny
    # structurally valid params with broken shapes: building the pipeline
    # succeeds, but the stage programs fail at trace time — exactly the
    # failure class preflight exists to catch before traffic
    bad = jax.tree.map(
        lambda a: np.zeros(np.shape(a)[:-1] + (np.shape(a)[-1] + 1,),
                           np.float32) if np.ndim(a) else a, p)
    in_q, out_q = queue.Queue(), queue.Queue()
    h = Defer(config=DeferConfig(microbatch=1, chunk=2)).run_defer(
        g, bad, None, in_q, out_q, num_stages=2)
    assert out_q.get(timeout=120) is END_OF_STREAM
    assert not h.healthy
