"""Flash-attention kernel: equivalence with reference attention.

Runs the identical Pallas kernel in interpreter mode on the CPU backend
(SURVEY.md §4: fake-backend testing), so the math under test is exactly what
compiles for the MXU on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu.ops import flash_attention
from defer_tpu.parallel.ring_attention import full_attention

# default CPU matmuls may run at reduced precision; the comparison below is
# between two f32 implementations, so the tolerance covers that
TOL = 5e-3


@pytest.mark.parametrize("shape,causal", [
    ((2, 3, 64, 64, 16), False),
    ((1, 2, 100, 100, 24), True),     # non-multiple of block: padding path
    ((2, 2, 37, 53, 8), False),       # Tq != Tk
    ((1, 1, 130, 130, 64), True),     # spills into a second q block
])
def test_matches_reference(shape, causal):
    b, h, tq, tk, d = shape
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, h, tq, d))
    k = jax.random.normal(ks[1], (b, h, tk, d))
    v = jax.random.normal(ks[2], (b, h, tk, d))
    out = flash_attention(q, k, v, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=TOL, rtol=TOL)


def test_multi_block_k_loop():
    """Accumulation across several K/V blocks (the online-softmax carry)."""
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (1, 2, 32, 16))
    k = jax.random.normal(ks[1], (1, 2, 96, 16))
    v = jax.random.normal(ks[2], (1, 2, 96, 16))
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=TOL, rtol=TOL)


def test_causal_masks_future():
    """Output at position t must not depend on keys/values after t."""
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (1, 1, 16, 8))
    k = jax.random.normal(ks[1], (1, 1, 16, 8))
    v = jax.random.normal(ks[2], (1, 1, 16, 8))
    out1 = flash_attention(q, k, v, causal=True)
    # perturb the last key/value; all but the last position must be unchanged
    k2 = k.at[:, :, -1].add(7.0)
    v2 = v.at[:, :, -1].add(-3.0)
    out2 = flash_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :, :-1]),
                               np.asarray(out2[:, :, :-1]), atol=1e-6)
    assert not np.allclose(np.asarray(out1[:, :, -1]),
                           np.asarray(out2[:, :, -1]))


def test_causal_decode_attends_to_full_prefix():
    """Tq=1 against a long K/V prefix (KV-cache decode): bottom-right
    causal alignment must admit every prefix position."""
    ks = jax.random.split(jax.random.key(6), 3)
    q = jax.random.normal(ks[0], (1, 2, 1, 16))
    k = jax.random.normal(ks[1], (1, 2, 48, 16))
    v = jax.random.normal(ks[2], (1, 2, 48, 16))
    out = flash_attention(q, k, v, causal=True)
    ref = full_attention(q, k, v, causal=False)  # full prefix visible
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=TOL, rtol=TOL)
    # chunked-decode shape (Tq=5 against Tk=48): bottom-right alignment,
    # oracle is full_attention's own bottom-right causal mask
    q5 = jax.random.normal(ks[0], (1, 2, 5, 16))
    out5 = flash_attention(q5, k, v, causal=True)
    ref5 = full_attention(q5, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out5), np.asarray(ref5),
                               atol=TOL, rtol=TOL)


def test_bfloat16_io():
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (1, 2, 64, 32), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, 2, 64, 32), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, 2, 64, 32), jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = full_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_transformer_block_flash_matches_xla():
    """The graph-level TransformerBlock gives the same output under both
    attention implementations."""
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.graph.ops import TransformerBlock

    outs = {}
    for impl in ("xla", "flash"):
        b = GraphBuilder(f"blk_{impl}")
        x = b.input((24, 32), jnp.float32)
        y = b.add(TransformerBlock(num_heads=2, attn_impl=impl), x,
                  name="blk")
        g = b.build()
        params = g.init(jax.random.key(4))
        xin = jax.random.normal(jax.random.key(5), (2, 24, 32))
        outs[impl] = np.asarray(g.apply(params, xin))
    np.testing.assert_allclose(outs["flash"], outs["xla"],
                               atol=TOL, rtol=TOL)
