"""GPT causal-LM training through the pipeline trainer.

The decoder family must train exactly like the other model families: the
full-sequence causal graph partitions at block boundaries and the generic
``PipelineTrainer`` differentiates the same switch+ppermute+scan program.
Also checks that trained weights flow back into the decode engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from defer_tpu import PipelineTrainer, SpmdPipeline, partition, pipeline_mesh
from defer_tpu.models import gpt_stage_cuts, gpt_tiny
from defer_tpu.runtime.decode import PipelinedDecoder

VOCAB = 61
SEQ = 12


def lm_loss(logits, ids):
    """Next-token cross entropy: predict ids[t+1] from position t."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = ids[:, 1:].astype(jnp.int32)
    pick = jnp.take_along_axis(logp[:, :-1], tgt[..., None], -1)[..., 0]
    return -jnp.mean(pick)


@pytest.fixture(scope="module")
def setup():
    graph = gpt_tiny(seq_len=SEQ, vocab=VOCAB)
    params = graph.init(jax.random.key(1))
    stages = partition(graph, gpt_stage_cuts(4, 4))
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=2, chunk=6)
    trainer = PipelineTrainer(pipe, lm_loss, optimizer=optax.adam(5e-3))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, VOCAB, (6, 2, SEQ))
    return graph, pipe, trainer, ids


def test_gpt_trains_and_loss_drops(setup):
    graph, pipe, trainer, ids = setup
    xs = ids.astype(np.float32)  # ids ride the f32 transfer buffer exactly
    losses = [trainer.step(xs, ids) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_trained_weights_deploy_to_decoder(setup):
    graph, pipe, trainer, ids = setup
    trained = trainer.trained_params()
    dec = PipelinedDecoder(graph, trained, num_stages=2, microbatch=2,
                           max_len=SEQ)
    toks = dec.generate(ids[0, :, :4].astype(np.int32), max_new_tokens=4)
    assert toks.shape == (2, 8)
    # decode agrees with the trained full graph, greedy next-token
    logits = graph.apply(trained, jnp.asarray(toks[:, :4], jnp.int32))
    nxt = np.asarray(jnp.argmax(logits[:, -1].astype(jnp.float32), -1))
    np.testing.assert_array_equal(toks[:, 4], nxt)
