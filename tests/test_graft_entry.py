"""The driver's entry points must always work: entry() compiles and
dryrun_multichip exercises every sharding (pp/dp ring + raw/int8 drains,
training step, sp ring attention, tp Megatron, ep MoE) on the virtual
mesh — the exact validation the driver runs between rounds."""

import jax


def test_entry_eval_shape():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (1, 1000)


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)  # asserts internally; must not raise
