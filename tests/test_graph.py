"""Graph IR + analysis unit tests (partitioner test strategy per SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import GraphBuilder, valid_cut_points, auto_cut_points
from defer_tpu.graph import ops, node_flops, total_flops, max_activation_elems
from defer_tpu.graph.viz import summary, to_dot


def diamond_graph():
    """input -> a -> (b1, b2) -> add -> d : only a, add, d are valid cuts."""
    b = GraphBuilder("diamond")
    x = b.input((4,))
    a = b.add(ops.Dense(8), x, name="a")
    b1 = b.add(ops.Dense(8), a, name="b1")
    b2 = b.add(ops.Dense(8), a, name="b2")
    m = b.add(ops.Add(), [b1, b2], name="merge")
    d = b.add(ops.Dense(3), m, name="d")
    return b.build()


def test_builder_shapes():
    g = diamond_graph()
    assert g.out_spec("a").shape == (8,)
    assert g.output_spec.shape == (3,)
    assert g.topo_order == ["a", "b1", "b2", "merge", "d"]
    assert g.predecessors("merge") == ("b1", "b2")


def test_apply_matches_manual():
    g = diamond_graph()
    params = g.init(jax.random.key(0))
    x = jnp.ones((2, 4))
    y = g.apply(params, x)
    a = x @ params["a"]["w"] + params["a"]["b"]
    b1 = a @ params["b1"]["w"] + params["b1"]["b"]
    b2 = a @ params["b2"]["w"] + params["b2"]["b"]
    manual = (b1 + b2) @ params["d"]["w"] + params["d"]["b"]
    np.testing.assert_allclose(np.asarray(y), np.asarray(manual), rtol=1e-6)


def test_apply_requires_input_or_seeds():
    g = diamond_graph()
    params = g.init(jax.random.key(0))
    with pytest.raises(TypeError, match="seeds"):
        g.apply(params)


def test_valid_cut_points_excludes_branch_interior():
    g = diamond_graph()
    cuts = valid_cut_points(g)
    # b1/b2 are inside the diamond: cutting there leaves 2 crossing tensors.
    assert cuts == ["a", "merge"]  # output node "d" excluded


def test_memoization_single_visit():
    """Shared ancestors are evaluated once (reference dag_util.py re-visits
    them once per fan-in path — SURVEY.md §3.5)."""
    calls = {"n": 0}

    class Counting(ops.Dense):
        def apply(self, params, x):
            calls["n"] += 1
            return super().apply(params, x)

    b = GraphBuilder("wide")
    x = b.input((4,))
    a = b.add(Counting(4), x, name="a")
    branches = [b.add(ops.Dense(4), a, name=f"br{i}") for i in range(4)]
    b.add(ops.Add(), branches, name="m")
    g = b.build()
    params = g.init(jax.random.key(0))
    calls["n"] = 0
    g.apply(params, jnp.ones((1, 4)))
    assert calls["n"] == 1


def test_auto_cut_points_balanced():
    b = GraphBuilder("chain")
    x = b.input((16,))
    for i in range(10):
        x = b.add(ops.Dense(16), x, name=f"fc{i}")
    g = b.build()
    cuts = auto_cut_points(g, 4)
    assert len(cuts) == 3
    order = g.topo_order
    assert [order.index(c) for c in cuts] == sorted(order.index(c) for c in cuts)
    with pytest.raises(ValueError):
        auto_cut_points(g, 50)  # more stages than cut points


def test_auto_cut_points_with_measured_costs():
    """costs= overrides the FLOP model: equal-FLOP chains cut evenly by
    default, but concentrated measured cost pulls every cut toward it."""
    b = GraphBuilder("chain")
    x = b.input((16,))
    for i in range(10):
        x = b.add(ops.Dense(16), x, name=f"fc{i}")
    g = b.build()
    even = auto_cut_points(g, 2)
    # all the real cost lives in the last two nodes: the midpoint cut
    # must move to just before them
    costs = {n: 1.0 for n in g.topo_order}
    costs["fc8"] = costs["fc9"] = 100.0
    skewed = auto_cut_points(g, 2, costs=costs)
    assert g.topo_order.index(skewed[0]) > g.topo_order.index(even[0])
    # cum costs: fc7=8, fc8=108, fc9=208; half-total=104 -> cut after fc8
    # (stages 108/100 — the balanced split the FLOP model can't see)
    assert skewed == ["fc8"]
    # a costs map that misses nodes is refused loudly
    with pytest.raises(ValueError, match="missing"):
        auto_cut_points(g, 2, costs={"fc0": 1.0})
    # sub-1.0 totals (measured SECONDS sum well below 1) must balance
    # identically to the same relative costs scaled up — the old
    # max(total, 1) clamp collapsed all cuts to the graph tail
    tiny = {n: 1e-5 for n in g.topo_order}
    assert auto_cut_points(g, 2, costs=tiny) == \
        auto_cut_points(g, 2, costs={n: 10.0 for n in g.topo_order})
    assert auto_cut_points(g, 4, costs=tiny) == \
        auto_cut_points(g, 4, costs={n: 10.0 for n in g.topo_order})


def test_measured_node_costs_integrates():
    """measured_node_costs produces a usable cost map for every node and
    auto_cut_points accepts it end to end (timings real, on this CPU)."""
    from defer_tpu.utils.profiling import measured_node_costs
    b = GraphBuilder("chain")
    x = b.input((16,))
    for i in range(6):
        x = b.add(ops.Dense(16), x, name=f"fc{i}")
    g = b.build()
    params = g.init(jax.random.key(1))
    costs = measured_node_costs(g, params, reps=2, k=8)
    assert set(costs) == set(g.topo_order)
    assert all(v > 0 for v in costs.values())
    cuts = auto_cut_points(g, 3, costs=costs)
    assert len(cuts) == 2


def test_flops_and_viz():
    g = diamond_graph()
    assert node_flops(g, "a") == 2 * 4 * 8
    assert total_flops(g) > 0
    assert max_activation_elems(g, ["a"]) >= 8
    dot = to_dot(g, {"a": 0, "b1": 1})
    assert "digraph" in dot and '"a"' in dot
    assert "merge" in summary(g)


def test_duplicate_and_unknown_nodes_rejected():
    b = GraphBuilder("bad")
    x = b.input((4,))
    b.add(ops.Dense(4), x, name="a")
    with pytest.raises(ValueError):
        b.add(ops.Dense(4), x, name="a")
    with pytest.raises(ValueError):
        b.add(ops.Dense(4), "nope")
