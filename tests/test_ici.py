"""Device-resident transport tier (docs/TRANSPORT.md): live jax.Array
handoff, same-mesh negotiation + ladder order, host-sync observability,
and the planner's ici pseudo-codec + host_sync term — the in-process
halves of ``scripts/ici_smoke.py``.
"""

import socket
import threading

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.obs import REGISTRY
from defer_tpu.runtime.node import (ChainDispatcher, StageNode,
                                    _normalize_hop_tiers)
from defer_tpu.transport.framed import (K_CTRL, K_TENSOR, K_TENSOR_SEQ,
                                        PROTOCOL_VERSION, recv_frame,
                                        send_ctrl)
from defer_tpu.transport.ici import (IciPipe, IciSender, grant_ici,
                                     offer_ici)
from defer_tpu.transport.shm import answer_tier_probe, offer_tier_ladder


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


def _hist_count(name: str) -> int:
    return int(REGISTRY.histogram(name).summary().get("count", 0))


# ---------------------------------------------------------------------------
# pipe semantics: live arrays, device placement
# ---------------------------------------------------------------------------

def test_pipe_same_device_passes_live_array_by_reference():
    p = IciPipe(depth=4)
    x = jax.device_put(np.arange(8, dtype=np.float32), jax.devices()[0])
    p.sender.dest_device = jax.devices()[0]
    p.sender.send(x)
    p.sender.send(x * 2, seq=5)
    p.sender.send_end()
    kind, got = p.receiver.get(1.0)
    assert kind == K_TENSOR and got is x  # BY REFERENCE: zero copies
    kind, (seq, got2) = p.receiver.get(1.0)
    assert kind == K_TENSOR_SEQ and seq == 5
    np.testing.assert_array_equal(np.asarray(got2), np.arange(8) * 2)
    assert p.sender.d2d == 0 and p.sender.device_pairs == set()


def test_pipe_cross_device_send_pays_exactly_one_device_put(host_devices):
    d0, d1 = host_devices[0], host_devices[1]
    p = IciPipe(depth=4)
    p.sender.dest_device = d1
    x = jax.device_put(np.arange(8, dtype=np.float32), d0)
    before = _counter("transport.ici_d2d")
    p.sender.send(x)
    kind, got = p.receiver.get(1.0)
    assert kind == K_TENSOR and got is not x
    assert next(iter(got.devices())).id == d1.id
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x))
    assert p.sender.d2d == 1
    assert p.sender.device_pairs == {(d0.id, d1.id)}
    assert _counter("transport.ici_d2d") == before + 1
    # a host (numpy) input is uploaded but is NOT a d2d transfer
    p.sender.send(np.ones(4, dtype=np.float32))
    _, got_np = p.receiver.get(1.0)
    assert next(iter(got_np.devices())).id == d1.id
    assert p.sender.d2d == 1


# ---------------------------------------------------------------------------
# grant validation: same process AND same mesh
# ---------------------------------------------------------------------------

def _probe(pipe: IciPipe, **over) -> dict:
    from defer_tpu.transport import ici as ici_mod
    import os
    token = ici_mod._register(pipe)
    msg = {"cmd": "tier_probe", "want": "ici", "pid": os.getpid(),
           "proto": PROTOCOL_VERSION, "token": token,
           "backend": jax.default_backend(),
           "platform": jax.devices()[0].platform,
           "device_ids": [jax.devices()[0].id]}
    msg.update(over)
    return msg


def test_grant_checks_in_order():
    assert grant_ici(_probe(IciPipe())) is not None
    assert grant_ici(_probe(IciPipe(), proto=PROTOCOL_VERSION + 1)) is None
    assert grant_ici(_probe(IciPipe(), pid=1)) is None
    assert grant_ici(_probe(IciPipe(), backend="tpu9")) is None
    # the same-mesh proof: an unresolvable device id refuses the grant
    assert grant_ici(_probe(IciPipe(), device_ids=[10 ** 6])) is None
    assert grant_ici(_probe(IciPipe(), device_ids=[])) is None
    assert grant_ici(_probe(IciPipe(), platform="warp")) is None
    msg = _probe(IciPipe())
    assert grant_ici(msg) is not None
    assert grant_ici(msg) is None  # token claims exactly once


# ---------------------------------------------------------------------------
# ladder order (the satellite regression): ici > local > shm > tcp
# ---------------------------------------------------------------------------

def _ladder_peer(conn, *, grants: dict):
    """Serve tier probes on ``conn``: grant a want iff grants[want]."""
    rx = None

    def run():
        nonlocal rx
        while True:
            kind, msg = recv_frame(conn)
            if kind != K_CTRL:
                return
            want = msg.get("want")
            if grants.get(want):
                _, rx = answer_tier_probe(conn, msg, accept=True,
                                          device=None)
                return
            send_ctrl(conn, {"cmd": "tier_reply", "tier": "tcp"})

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, lambda: rx


def test_auto_ladder_prefers_ici_over_local():
    a, b = socket.socketpair()
    t, _rx = _ladder_peer(b, grants={"ici": True, "local": True})
    fb0 = _counter("transport.tier_fallback")
    tier, tx, fell = offer_tier_ladder(a, tier="auto", hop="t")
    t.join(timeout=5)
    assert tier == "ici" and isinstance(tx, IciSender) and not fell
    assert _counter("transport.tier_fallback") == fb0
    a.close(), b.close()


def test_refused_ici_degrades_to_local_not_tcp():
    """ici refused (foreign mesh) but local granted: the hop lands on
    local — NOT tcp — and no fallback is recorded (a granted rung is
    not a degradation)."""
    a, b = socket.socketpair()
    t, _rx = _ladder_peer(b, grants={"ici": False, "local": True})
    fb0 = _counter("transport.tier_fallback")
    tier, tx, fell = offer_tier_ladder(a, tier="auto", hop="t")
    t.join(timeout=5)
    assert tier == "local" and tx is not None and not fell
    assert _counter("transport.tier_fallback") == fb0
    a.close(), b.close()


def test_all_rungs_refused_counts_exactly_one_fallback():
    a, b = socket.socketpair()

    def refuse_all():
        for _ in range(3):  # ici, local, shm
            kind, msg = recv_frame(b)
            assert kind == K_CTRL
            send_ctrl(b, {"cmd": "tier_reply", "tier": "tcp"})

    t = threading.Thread(target=refuse_all, daemon=True)
    t.start()
    fb0 = _counter("transport.tier_fallback")
    hop0 = _counter("transport.tier_fallback.hopX")
    tier, tx, fell = offer_tier_ladder(a, tier="auto", hop="hopX")
    t.join(timeout=5)
    assert (tier, tx, fell) == ("tcp", None, True)
    assert _counter("transport.tier_fallback") == fb0 + 1
    assert _counter("transport.tier_fallback.hopX") == hop0 + 1
    a.close(), b.close()


def test_pinned_tiers_offer_only_their_rung():
    """``--tier shm``/``local``/``ici`` pins suppress every other offer
    — the audit half of the delay-codec-bench satellite: a pinned hop
    sends exactly one probe, and ``tcp`` sends none (covered by the
    chain fixture's tcp baseline)."""
    for pin, n_probes in (("shm", 1), ("local", 1), ("ici", 1)):
        a, b = socket.socketpair()
        wants = []

        def peer():
            while True:
                try:
                    kind, msg = recv_frame(b)
                except (ConnectionError, OSError):
                    return
                if kind != K_CTRL:
                    return
                wants.append(msg.get("want"))
                send_ctrl(b, {"cmd": "tier_reply", "tier": "tcp"})

        t = threading.Thread(target=peer, daemon=True)
        t.start()
        tier, tx, fell = offer_tier_ladder(a, tier=pin, hop="t")
        assert (tier, tx, fell) == ("tcp", None, True)
        assert wants == [pin], f"pin {pin} leaked offers: {wants}"
        a.close(), b.close()
        t.join(timeout=5)


# ---------------------------------------------------------------------------
# in-process chains: device-resident end to end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def _run_chain_inproc(stages, params, xs, *, tier, devices=None,
                      accepts=None):
    n = len(stages)
    nodes = [StageNode(None, "127.0.0.1:0", None, tier=tier,
                       tier_accept=True if accepts is None else accepts[i])
             for i in range(n)]
    addrs = [f"127.0.0.1:{nd.address[1]}" for nd in nodes]
    threads = [threading.Thread(target=nd.serve, daemon=True)
               for nd in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw", tier=tier)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0],
                    tiers=[tier] * n, devices=devices)
        outs = disp.stream(xs)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, stats, disp


@pytest.fixture(scope="module")
def chain3(tiny):
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    outs, stats, _ = _run_chain_inproc(stages, params, xs, tier="tcp")
    return g, params, stages, xs, outs, stats


def test_tcp_pin_suppresses_every_offer(chain3):
    """The delay-codec benches pin ``--tier tcp`` so shm cannot bypass
    their codecs — the pin must suppress the new ici offer too: the
    tcp baseline chain moved zero frames through ici (or local) pipes
    and negotiated tcp everywhere."""
    _, _, _, xs, _, stats = chain3
    assert [s["tier"] for s in stats] == ["tcp"] * 3
    assert [s["tier_in"] for s in stats] == [None] * 3  # never probed
    assert [s["ici_d2d"] for s in stats] == [0] * 3


def test_ici_chain_device_resident_end_to_end(chain3, host_devices):
    """The tentpole acceptance, in-process half: every hop (dispatcher
    edges included) negotiates ici under ``auto``, outputs are
    byte-identical to the all-TCP chain, ZERO ``codec.*`` and ZERO
    ``host_sync`` samples land on any ici hop (the round-trip is GONE,
    not just cheaper), at least one hop performs a real cross-device
    ``device_put`` with distinct (src, dst) device ids, and the ONE
    host sync per frame happens at the dispatcher's result edge."""
    g, params, stages, xs, base, _ = chain3
    enc0, dec0 = _hist_count("codec.encode_s"), _hist_count("codec.decode_s")
    hs0 = _hist_count("node.host_sync_s")
    chs0 = _hist_count("chain.host_sync_s")
    if0 = _counter("transport.ici_frames")
    outs, stats, disp = _run_chain_inproc(stages, params, xs,
                                          tier="auto",
                                          devices=[0, 1, 2])
    assert [s["tier"] for s in stats] == ["ici"] * 3
    assert [s["tier_in"] for s in stats] == ["ici"] * 3
    assert (disp.tier_out, disp.tier_in) == ("ici", "ici")
    assert [s["device"] for s in stats] == [0, 1, 2]
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # zero codec work AND zero host syncs on the stage nodes
    assert _hist_count("codec.encode_s") == enc0
    assert _hist_count("codec.decode_s") == dec0
    assert _hist_count("node.host_sync_s") == hs0
    assert [s["host_sync_s"]["count"] for s in stats] == [0] * 3
    # real cross-device transfers: stage0 -> dev1, stage1 -> dev2
    assert stats[0]["ici_d2d"] == len(xs)
    assert stats[0]["ici_device_pairs"] == [[0, 1]]
    assert stats[1]["ici_device_pairs"] == [[1, 2]]
    # 4 hops (disp->s0->s1->s2->result) x frames rode the ici pipes...
    assert _counter("transport.ici_frames") - if0 == 4 * len(xs)
    # ...and the result edge host-synced exactly once per frame
    assert _hist_count("chain.host_sync_s") - chs0 == len(xs)


def test_local_chain_pays_host_sync_ici_removes(chain3):
    """The host-sync observability satellite: a local-tier chain
    records exactly one host_sync sample per frame per stage — the
    measured cost the planner's host_sync term models and the ici
    chain's zero count proves gone."""
    g, params, stages, xs, _, _ = chain3
    outs, stats, _ = _run_chain_inproc(stages, params, xs, tier="local")
    assert [s["tier"] for s in stats] == ["local"] * 3
    assert [s["host_sync_s"]["count"] for s in stats] == [len(xs)] * 3
    assert all(s["host_sync_s"]["max"] >= 0 for s in stats)


def test_refused_ici_chain_degrades_with_labeled_fallback(chain3):
    """A pinned ici hop whose peer refuses degrades to tcp with the
    stream byte-identical and the hop's fallback attributable."""
    g, params, stages, xs, base, _ = chain3
    before = _counter("transport.tier_fallback")
    outs, stats, _ = _run_chain_inproc(stages, params, xs, tier="ici",
                                       accepts=[True, False, True])
    assert _counter("transport.tier_fallback") > before
    by_stage = {s["stage"]: s for s in stats}
    assert by_stage[0]["tier"] == "tcp"    # its offer was refused
    assert by_stage[0]["tier_fallbacks"] == 1
    assert by_stage[1]["tier"] == "ici"    # stage 2 still granted
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_extension_dtype_roundtrips_every_tier():
    """bfloat16 activations (ops.Cast — the TPU-native regime) cross
    tcp frames AND shm ring descriptors as themselves: extension
    dtypes ship by NAME when numpy's ``.str`` is an opaque void alias
    (``wire_dtype``/``dtype_from_wire``), so a bf16 boundary is
    byte-identical across every tier instead of decoding as raw
    bytes."""
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops

    b = GraphBuilder("bf16chain")
    x = b.input((32,))
    x = b.add(ops.Dense(32), x, name="d0")
    x = b.add(ops.Cast("bfloat16"), x, name="half")
    b.add(ops.Dense(16), x, name="head")
    g = b.build()
    params = g.init(jax.random.key(1))
    stages = partition(g, ["d0", "half"])  # hop 1->2 carries bf16
    rng = np.random.default_rng(2)
    xs = [rng.standard_normal((1, 32)).astype(np.float32)
          for _ in range(3)]
    base, _, _ = _run_chain_inproc(stages, params, xs, tier="tcp")
    assert np.asarray(base[0]).dtype == np.dtype("bfloat16")
    for tier in ("shm", "auto"):
        outs, stats, _ = _run_chain_inproc(stages, params, xs, tier=tier)
        assert stats[1]["tier"] == ("shm" if tier == "shm" else "ici")
        for a, bb in zip(base, outs):
            assert np.asarray(bb).dtype == np.dtype("bfloat16")
            np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))


# ---------------------------------------------------------------------------
# guard rails
# ---------------------------------------------------------------------------

def test_ici_pin_on_fan_role_node_rejected():
    with pytest.raises(ValueError, match="fan paths"):
        StageNode(None, "127.0.0.1:0", None, tier="ici", replica=0)
    with pytest.raises(ValueError, match="fan paths"):
        StageNode(None, "127.0.0.1:0", None, tier="ici", branch=1)
    with pytest.raises(ValueError, match="fan paths"):
        StageNode(None, "127.0.0.1:0", "127.0.0.1:1,127.0.0.1:2",
                  tier="ici")


def test_ici_hop_tiers_validation():
    # adjacent replication never composes with a device-resident hop
    with pytest.raises(ValueError, match="replicated"):
        _normalize_hop_tiers(["ici", "tcp"], 3, [1, 2, 1], "tcp")
    # the chain-wide default expansion is validated the same way
    with pytest.raises(ValueError, match="replicated"):
        _normalize_hop_tiers(None, 3, [1, 2, 1], "ici")
    assert _normalize_hop_tiers(["ici", "auto"], 3, [1, 1, 1], "tcp") \
        == ["ici", "auto"]
    with pytest.raises(ValueError, match="ici"):
        _normalize_hop_tiers(["warp"], 2, [1, 1], "tcp")


def test_ici_hop_tiers_require_overlap(tiny):
    from defer_tpu.runtime.node import run_chain
    g, params = tiny
    stages = partition(g, num_stages=3)
    with pytest.raises(ValueError, match="overlap"):
        run_chain(stages, params, [], overlap=False,
                  hop_tiers=["ici", "tcp"])


def test_chain_level_ici_tier_rejected_loudly(tiny):
    """tier='ici'/'local' as the CHAIN tier also claims the dispatcher
    edges — always cross-process in a spawned chain, so the pin could
    only silently degrade; rejected with a pointer at hop_tiers."""
    from defer_tpu.runtime.node import run_chain
    g, params = tiny
    stages = partition(g, num_stages=3)
    for t in ("ici", "local"):
        with pytest.raises(ValueError, match="hop_tiers"):
            run_chain(stages, params, [], tier=t)


def test_device_pin_validation(tiny):
    g, params = tiny
    with pytest.raises(ValueError, match="out of range"):
        StageNode(None, "127.0.0.1:0", None, device=99)
    from defer_tpu.runtime.node import run_chain
    stages = partition(g, num_stages=3)
    with pytest.raises(ValueError, match="out of range"):
        run_chain(stages, params, [], device_map={9: 0})
    with pytest.raises(ValueError, match="host mesh"):
        run_chain(stages, params, [], devices=2, device_map={0: 5})
    with pytest.raises(ValueError, match=">= 0"):
        run_chain(stages, params, [], device_map={0: -1})
    # device-tier fusion renumbers stages: a pre-fusion pin would land
    # on the wrong stage silently — rejected loudly instead
    with pytest.raises(ValueError, match="fusion"):
        run_chain(stages, params, [], device_map={2: 1},
                  hop_tiers=["device", "ici"])


def test_force_host_device_count_after_init_skips_with_reason():
    from defer_tpu.utils.compat import force_host_device_count
    ok, why = force_host_device_count(len(jax.devices()))
    assert ok and "already initialized" in why
    ok, why = force_host_device_count(len(jax.devices()) + 1)
    assert not ok and "already initialized" in why


# ---------------------------------------------------------------------------
# planner: the ici pseudo-codec + host_sync term
# ---------------------------------------------------------------------------

def _fat_boundary_model():
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel

    b = GraphBuilder("fatcut")
    x = b.input((4096,))
    for i in range(3):
        x = b.add(ops.Dense(4096), x, name=f"d{i}")
    x = b.add(ops.Dense(8), x, name="head")
    g = b.build()
    costs = {"d0": 1e-3, "d1": 1e-3, "d2": 1e-3, "head": 1e-4}
    return g, StageCostModel(g, gen="v4", link_bw_s=1e6, node_costs=costs)


def test_tier_ordering_is_principled():
    """The acceptance bar: device <= ici <= local <= shm <= tcp,
    STRICT on a fat boundary — because every non-device-resident tier
    pays the host_sync round-trip and ici pays only the interconnect."""
    g, cm = _fat_boundary_model()
    cost = {t: cm.with_hop_tiers({"d1": t}).comm_seconds("d1", t)
            for t in ("device", "ici", "local", "shm")}
    cost["tcp"] = cm.best_codec("d1")[1]
    assert cost["device"] < cost["ici"] < cost["local"] \
        < cost["shm"] < cost["tcp"]
    # the ici hop is exactly the interconnect pass: no host term at all
    assert cost["ici"] == pytest.approx(cm.cut_bytes("d1") / cm.ici_bw_s)
    # everything else carries the host_sync round-trip
    hs = cm.host_sync_seconds("d1")
    assert hs > 0
    assert cost["local"] == pytest.approx(
        cm.cut_bytes("d1") / cm.local_bw_s + hs)


def test_solver_exploits_ici_map_and_json_roundtrip():
    from defer_tpu.plan import plan_from_json, replan, solve

    g, cm = _fat_boundary_model()
    p_tcp = solve(g, 3, cm)
    tiers = {c: "ici" for c in ("d0", "d1", "d2")}
    p_ici = solve(g, 3, cm, hop_tiers=tiers)
    assert p_ici.bottleneck_s < p_tcp.bottleneck_s  # STRICT: comm-bound
    assert set(p_ici.codecs) == {"ici"}
    doc = p_ici.to_json()
    assert doc["hop_tiers"] == ["ici", "ici"]
    assert doc["cost_model"]["ici_bw_s"] == cm.ici_bw_s
    assert doc["cost_model"]["host_sync_bw_s"] == cm.host_sync_bw_s
    assert plan_from_json(doc).hop_tiers == ["ici", "ici"]
    # the tier (and its bandwidths) survive a replan
    rp = replan(g, p_ici, {0: 2e-3, 1: 1e-3, 2: 1e-3},
                cm.with_hop_tiers(tiers))
    assert set(rp.new_plan.hop_tiers) == {"ici"}
    assert set(rp.old_plan_corrected.hop_tiers) == {"ici"}


def test_ici_tier_never_applies_to_fan_hops():
    g, cm = _fat_boundary_model()
    cm = cm.with_hop_tiers({"d1": "ici"})
    name, s = cm.best_codec_replicated("d1", 1, 1)
    assert name == "ici"
    name2, s2 = cm.best_codec_replicated("d1", 2, 1)
    assert name2 != "ici" and s2 > s


def test_dag_fan_boundary_rejects_ici():
    """Acceptance bar: a hop-tier map with ici on a fan boundary (the
    fork of a branch region) is rejected loudly."""
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel
    from defer_tpu.plan.dag import solve_dag

    b = GraphBuilder("fork2")
    x = b.add(ops.Dense(16), b.input((16,)), name="stem")
    left = b.add(ops.Dense(16), x, name="l0")
    right = b.add(ops.Dense(16), x, name="r0")
    b.add(ops.Add(), [left, right], name="merge")
    g = b.build()
    costs = {n: 1e-3 for n in g.topo_order}
    cm = StageCostModel(g, gen="v5e", link_bw_s=1e12, node_costs=costs)
    with pytest.raises(ValueError, match="wire-framed"):
        solve_dag(g, cm, num_nodes=4, hop_tiers={"stem": "ici"})


def test_monitor_renders_host_sync_column(capsys):
    from defer_tpu.cli import _render_monitor
    row = {"stage": 0, "replica": None, "branch": None, "join": 0,
           "tier": "ici", "tier_fallbacks": 0, "alive": True,
           "throughput_per_s": 1.0,
           "infer_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
           "host_sync_ms": {"p50": 0.0, "count": 0},
           "rx_q": 0, "tx_q": 0, "rx_hi": 0, "tx_hi": 0, "inflight": 0,
           "rx_bytes_per_s": 0, "tx_bytes_per_s": 0, "processed": 5,
           "addr": "x"}
    row2 = dict(row, stage=1, tier="local",
                host_sync_ms={"p50": 1.25, "count": 5})
    _render_monitor([row, row2], None, [], {}, clear=False)
    out = capsys.readouterr().out
    assert "HS50" in out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert "-" in lines[1].split()  # zero samples renders the proof mark
    assert "1.250" in lines[2]
