"""Black-box journal (obs/journal.py) + postmortem (obs/postmortem.py):
crash-safety of the on-disk format — torn final writes, ring rotation
under the size cap, kill -9 mid-spill — and the offline collector's
alignment, first-fault verdict, and loud-partial-bundle behavior."""

import json
import os
import signal
import struct
import subprocess
import sys
import time
import zlib

from defer_tpu.obs import (collect_postmortem, read_journal,
                           read_process_journals, start_journal,
                           stop_journal)
from defer_tpu.obs.events import emit
from defer_tpu.obs.journal import (JOURNAL_VERSION, JournalWriter,
                                   read_segment)

_HDR = struct.Struct("<II")


def _write_journal(root, proc, *records, pid=None):
    """One on-disk journal with controlled records.  The writer's own
    meta + real anchor come first (delta ~0: the tracer timeline IS
    wall-anchored), then the synthetic records."""
    w = JournalWriter(root, proc, pid=pid)
    for r in records:
        w.append(r)
    w.flush()
    w.close()
    return w


def _ev(proc, seq, t_us, kind="admit", **data):
    return {"proc": proc, "seq": seq, "t_us": t_us, "kind": kind,
            "data": data}


# ---------------------------------------------------------------------------
# writer/reader round trip + torn writes
# ---------------------------------------------------------------------------

def test_roundtrip_and_self_describing_meta(tmp_path):
    root = str(tmp_path)
    w = JournalWriter(root, "stage1.r0", pid=4242)
    w.append({"rec": "events", "t_us": 10,
              "events": [_ev("stage1.r0", 0, 10)], "dropped": 0})
    w.flush()
    w.close()
    j = read_journal(w.dir)
    assert j["proc"] == "stage1.r0" and j["pid"] == 4242
    assert j["version"] == JOURNAL_VERSION
    assert not j["truncated"] and not j["warnings"]
    kinds = [r["rec"] for r in j["records"]]
    assert kinds[:2] == ["meta", "anchor"]      # every segment leads
    assert "events" in kinds


def test_torn_final_write_truncates_at_the_tear(tmp_path):
    w = JournalWriter(str(tmp_path), "p")
    for i in range(5):
        w.append({"rec": "events", "t_us": i,
                  "events": [_ev("p", i, i)], "dropped": 0})
    w.flush()
    w.close()
    seg = w.segments()[-1][0]
    whole = len(read_segment(seg)[0])
    # a kill -9 mid-write leaves a half-record: short payload
    with open(seg, "ab") as fh:
        fh.write(_HDR.pack(123, 999) + b"{\"rec")
    records, truncated = read_segment(seg)
    assert truncated and len(records) == whole

    # ... or a full-length payload whose bytes lie (CRC mismatch)
    payload = b'{"rec":"events"}'
    with open(seg, "ab") as fh:
        fh.write(_HDR.pack((zlib.crc32(payload) ^ 1) & 0xFFFFFFFF,
                           len(payload)) + payload)
    records2, truncated2 = read_segment(seg)
    assert truncated2 and len(records2) == whole

    # the journal-level reader reports the tear but keeps the story
    j = read_journal(w.dir)
    assert j["truncated"]
    assert len([r for r in j["records"] if r["rec"] == "events"]) == 5


def test_mid_ring_tear_warns_about_lost_evidence(tmp_path):
    w = JournalWriter(str(tmp_path), "p", segment_bytes=4096)
    blob = "x" * 600
    while w._seg_seq < 3:          # force >= 2 closed segments
        w.append({"rec": "events", "t_us": 0, "events": [], "pad": blob})
    w.flush()
    w.close()
    first_seg = w.segments()[0][0]
    with open(first_seg, "r+b") as fh:
        fh.seek(20)
        fh.write(b"\xff\xff\xff\xff")          # corrupt mid-segment
    j = read_journal(w.dir)
    assert j["truncated"]
    assert any("torn mid-ring" in wmsg for wmsg in j["warnings"])


def test_segment_ring_rotates_and_caps(tmp_path):
    w = JournalWriter(str(tmp_path), "p", segment_bytes=4096,
                      max_bytes=4096 * 2)
    blob = "y" * 200
    for i in range(200):
        w.append({"rec": "events", "t_us": i, "events": [], "pad": blob})
    w.flush()
    w.close()
    assert w.segments_dropped > 0
    live = w.segments()
    assert sum(sz for _, sz in live) <= 4096 * 2 + 4096  # cap + active
    # the survivors still read clean, and every segment self-describes
    j = read_journal(w.dir)
    assert j["version"] == JOURNAL_VERSION and not j["truncated"]
    metas = [r for r in j["records"] if r["rec"] == "meta"]
    assert len(metas) == len(live)


def test_kill9_mid_spill_leaves_readable_journal(tmp_path):
    """An actual SIGKILL between flushes: whatever reached the kernel
    is a readable journal; the collector explains it without any live
    process."""
    root = str(tmp_path / "j")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = (
        "import sys, time\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from defer_tpu.obs import start_journal\n"
        "from defer_tpu.obs.events import emit\n"
        f"start_journal({root!r}, 'victim', interval_s=0.05)\n"
        "i = 0\n"
        "while True:\n"
        "    emit('admit', rid=i); i += 1\n"
        "    time.sleep(0.01)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", child], env=env)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            js = read_process_journals(root)
            if js and any(r["rec"] == "events" for r in js[0]["records"]):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("victim never spilled an events record")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    js = read_process_journals(root)
    assert len(js) == 1 and js[0]["proc"] == "victim"
    evs = [e for r in js[0]["records"] if r["rec"] == "events"
           for e in r["events"]]
    assert evs and evs[0]["kind"] == "journal"  # boot event included
    bundle = collect_postmortem(root, out_dir=str(tmp_path / "b"),
                                reason="test kill9")
    assert [p["proc"] for p in bundle["procs"]] == ["victim"]
    assert bundle["timeline"]


# ---------------------------------------------------------------------------
# the spiller singleton
# ---------------------------------------------------------------------------

def test_spiller_writes_events_and_snapshots(tmp_path):
    root = str(tmp_path)
    try:
        start_journal(root, "unit", interval_s=0.05, snapshot_every=1,
                      snapshot_fn=lambda: {"rows": 1})
        emit("admit", rid=1)
        time.sleep(0.3)
    finally:
        stop_journal()
    stop_journal()                              # idempotent
    js = read_process_journals(root)
    assert len(js) == 1
    recs = js[0]["records"]
    kinds = {r["rec"] for r in recs}
    assert {"meta", "anchor", "events", "snapshot"} <= kinds
    snap = [r for r in recs if r["rec"] == "snapshot"][-1]
    assert snap["payload"] == {"rows": 1}
    emitted = [e for r in recs if r["rec"] == "events"
               for e in r["events"]]
    assert any(e["kind"] == "admit" and e["data"].get("rid") == 1
               for e in emitted)


# ---------------------------------------------------------------------------
# postmortem: partial bundles, alignment, verdict
# ---------------------------------------------------------------------------

def test_missing_and_empty_roots_yield_loud_partial_bundle(tmp_path):
    bundle = collect_postmortem(str(tmp_path / "nope"),
                                out_dir=str(tmp_path / "b1"))
    assert any("PARTIAL BUNDLE" in w for w in bundle["warnings"])
    assert bundle["procs"] == [] and bundle["timeline"] == []
    assert os.path.exists(os.path.join(str(tmp_path / "b1"),
                                       "bundle.json"))

    empty = tmp_path / "empty"
    empty.mkdir()
    bundle2 = collect_postmortem(str(empty), out_dir=str(tmp_path / "b2"))
    assert any("PARTIAL BUNDLE" in w for w in bundle2["warnings"])

    # a proc dir with no segments: a named warning, not a crash
    (empty / "ghost@7").mkdir()
    bundle3 = collect_postmortem(str(empty), out_dir=str(tmp_path / "b3"))
    assert any("no segments" in w for w in bundle3["warnings"])


def test_alignment_uses_last_anchor_delta(tmp_path):
    """Events stamped on a skewed process clock land on the wall axis:
    the LAST anchor's wall_us - t_us shifts everything."""
    root = str(tmp_path)
    w = JournalWriter(root, "skewed")
    delta = 5_000_000
    w.append({"rec": "anchor", "t_us": 1_000, "wall_us": 1_000 + delta})
    w.append({"rec": "events", "t_us": 2_000,
              "events": [_ev("skewed", 0, 1_500)], "dropped": 0})
    w.flush()
    w.close()
    bundle = collect_postmortem(root, out_dir=str(tmp_path / "b"))
    p = bundle["procs"][0]
    assert p["delta_us"] == delta
    ev = [e for e in bundle["timeline"] if e["kind"] == "admit"][0]
    assert ev["t_us"] == 1_500 + delta


def test_verdict_names_first_fault_and_ranks_casualties(tmp_path):
    """Three dead journals: stage1 stops 5s early, stage0's final
    snapshot shows a saturated tx watermark (backed up), stage2
    starved downstream.  The verdict must blame stage1 from the
    journal-stop evidence and rank stage2 (downstream) first."""
    root = str(tmp_path)
    base = time.time_ns() // 1_000
    _write_journal(root, "stage1", {
        "rec": "events", "t_us": base,
        "events": [_ev("stage1", 0, base)], "dropped": 0})
    _write_journal(root, "stage0", {
        "rec": "events", "t_us": base + 5_000_000,
        "events": [_ev("stage0", 0, base + 5_000_000)], "dropped": 0,
    }, {
        "rec": "snapshot", "t_us": base + 5_000_000,
        "payload": {"queues": {"tx_depth": 8, "tx_hi": 8,
                               "rx_depth": 8, "rx_hi": 0}}})
    _write_journal(root, "stage2", {
        "rec": "events", "t_us": base + 5_000_000,
        "events": [_ev("stage2", 0, base + 5_000_000)], "dropped": 0})
    bundle = collect_postmortem(root, out_dir=str(tmp_path / "b"),
                                reason="unit")
    v = bundle["verdict"]
    assert v["first_fault"] == "stage1"
    assert any("stops at" in e for e in v["evidence"])
    cas = v["casualties"]
    assert [c["proc"] for c in cas] == ["stage2", "stage0"]
    assert cas[0]["role"] == "downstream"
    assert cas[1]["role"] == "upstream"
    assert cas[1]["saturated"] == ["tx watermark 8/8"]
    # the bundle hit disk: json + perfetto trace
    out = bundle["out_dir"]
    doc = json.load(open(os.path.join(out, "trace.json")))
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert names == {"stage0", "stage1", "stage2"}


def test_fatal_event_names_victim_when_no_journal_stopped(tmp_path):
    """A respawned replica keeps journaling, so nothing 'stops' — the
    supervisor's replica_respawn event carries the blame instead."""
    root = str(tmp_path)
    base = time.time_ns() // 1_000
    _write_journal(root, "dispatcher", {
        "rec": "events", "t_us": base,
        "events": [_ev("dispatcher", 0, base, kind="replica_respawn",
                       stage=1, replica=0, rc=-9)], "dropped": 0})
    bundle = collect_postmortem(root, out_dir=str(tmp_path / "b"))
    v = bundle["verdict"]
    assert v["first_fault"] == "stage1.r0"
    assert v["fatal_event"]["kind"] == "replica_respawn"


def test_evidence_gap_warning_on_dropped_events(tmp_path):
    root = str(tmp_path)
    _write_journal(root, "p", {
        "rec": "events", "t_us": 50,
        "events": [_ev("p", 9, 50)], "dropped": 7})
    bundle = collect_postmortem(root, out_dir=str(tmp_path / "b"))
    assert bundle["events_dropped"] == 7
    assert bundle["verdict"]["events_dropped"] == 7
    assert any("EVIDENCE GAP" in w for w in bundle["warnings"])
