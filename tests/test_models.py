"""Model-zoo tests: every BASELINE.md family builds, has the advertised cut
points, and survives partition-equivalence through the SPMD pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import (SpmdPipeline, partition, pipeline_mesh,
                       valid_cut_points)
from defer_tpu import models as M


@pytest.mark.parametrize("factory,in_shape,in_dtype", [
    (M.vgg_tiny, (32, 32, 3), np.float32),
    (M.inception_tiny, (75, 75, 3), np.float32),
    (M.mobilenet_tiny, (32, 32, 3), np.float32),
    (M.bert_tiny, (16,), np.int32),
])
def test_tiny_models_build_and_run(factory, in_shape, in_dtype):
    g = factory()
    params = g.init(jax.random.key(0))
    if in_dtype == np.int32:
        x = jnp.zeros((2,) + in_shape, jnp.int32) + 3
    else:
        x = jax.random.normal(jax.random.key(1), (2,) + in_shape)
    y = jax.jit(g.apply)(params, x)
    assert np.isfinite(np.asarray(y, np.float32)).all()


@pytest.mark.parametrize("factory,num_stages,in_shape,in_dtype", [
    (M.vgg_tiny, 4, (32, 32, 3), np.float32),
    (M.inception_tiny, 6, (75, 75, 3), np.float32),
    (M.mobilenet_tiny, 2, (32, 32, 3), np.float32),
    (M.bert_tiny, 4, (16,), np.int32),
])
def test_pipeline_equivalence_all_families(factory, num_stages, in_shape,
                                           in_dtype):
    """The BASELINE.md configs, tiny-scale: pipeline == single program."""
    g = factory()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=num_stages)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(num_stages),
                        microbatch=1, chunk=4)
    rng = np.random.RandomState(0)
    if in_dtype == np.int32:
        inputs = rng.randint(0, 99, size=(3, 1) + in_shape).astype(np.int32)
    else:
        inputs = rng.randn(3, 1, *in_shape).astype(np.float32)
    out = pipe.run(inputs)
    fn = jax.jit(g.apply)
    ref = np.stack([np.asarray(fn(params, jnp.asarray(x)), np.float32)
                    for x in inputs])
    np.testing.assert_allclose(out, ref, rtol=3e-4, atol=3e-4)


def test_inception_cuts_are_block_boundaries_only():
    """Branching DAG: nothing inside an inception block is a valid cut —
    the articulation analysis must only offer stem nodes and mixed_k
    concats (SURVEY.md §7 hard part 3)."""
    g = M.inception_tiny()
    cuts = set(valid_cut_points(g))
    for idx in range(11):
        assert f"mixed_{idx}" in cuts
    # branch-interior nodes of the first A block must not be valid cuts
    mixed0_inputs = g.predecessors("mixed_0")
    for n in mixed0_inputs:
        assert n not in cuts, f"branch tail {n} wrongly valid"


def test_advertised_cut_lists_are_valid():
    for factory, cut_list in [
        (M.bert_tiny, ["block_0", "block_1", "block_2"]),
    ]:
        g = factory()
        stages = partition(g, cut_list)
        assert len(stages) == len(cut_list) + 1


def test_full_size_graphs_build():
    """Full-size graphs build with correct structure (no params/compute)."""
    g = M.resnet50()
    assert g.out_spec("add_15").shape == (7, 7, 2048)
    assert set(M.RESNET50_8STAGE_CUTS) <= set(valid_cut_points(g))
    g = M.vgg19()
    assert g.output_spec.shape == (1000,)
    assert set(M.VGG19_4STAGE_CUTS) <= set(valid_cut_points(g))
    g = M.mobilenet_v2()
    assert set(M.MOBILENETV2_2STAGE_CUTS) <= set(valid_cut_points(g))
    g = M.bert_base()
    assert g.output_spec.shape == (768,)
    assert set(M.BERT_BASE_12STAGE_CUTS) <= set(valid_cut_points(g))
    g = M.inception_v3()
    assert set(M.INCEPTION_6STAGE_CUTS) <= set(valid_cut_points(g))
