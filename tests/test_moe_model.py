"""MoE transformer family: pipeline partition equivalence + cut points."""

import numpy as np

import jax

from defer_tpu import Defer, DeferConfig, partition, valid_cut_points
from defer_tpu.models import moe_stage_cuts, moe_tiny


def test_moe_pipeline_matches_full():
    g = moe_tiny()
    p = g.init(jax.random.key(0))
    ids = (np.arange(3 * 1 * 16).reshape(3, 1, 16) % 100)
    ref = np.stack([np.asarray(g.apply(p, i)) for i in ids])
    out = Defer(config=DeferConfig(microbatch=1, chunk=3)).run(
        g, p, ids.astype(np.float32), cut_points=moe_stage_cuts(2))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_moe_cut_points_are_articulation():
    g = moe_tiny()
    cuts = set(valid_cut_points(g))
    for c in moe_stage_cuts(2):
        assert c in cuts
    stages = partition(g, moe_stage_cuts(2))
    assert len(stages) == 2
