"""MPMD relay pipeline: per-stage programs + device_put relay (the execution
model closest to the reference's socket chain, used as correctness oracle)."""

import jax
import numpy as np

from defer_tpu import MpmdPipeline, partition
from defer_tpu.models import resnet_tiny


def test_mpmd_matches_full_model():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=4)
    pipe = MpmdPipeline(stages, params, microbatch=2)
    inputs = np.asarray(jax.random.normal(jax.random.key(1), (3, 2, 32, 32, 3)))
    out = pipe.run(inputs)
    fn = jax.jit(g.apply)
    ref = np.stack([np.asarray(fn(params, x), np.float32) for x in inputs])
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
    assert pipe.metrics.inferences == 6
    # stages really landed on distinct devices
    assert len({d for d in pipe.devices}) == 4


def test_mpmd_more_stages_than_devices():
    """Round-robin placement keeps working when stages > devices."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=4)
    pipe = MpmdPipeline(stages, params, devices=jax.devices()[:2])
    inputs = np.asarray(jax.random.normal(jax.random.key(2), (2, 1, 32, 32, 3)))
    out = pipe.run(inputs)
    fn = jax.jit(g.apply)
    ref = np.stack([np.asarray(fn(params, x), np.float32) for x in inputs])
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_mpmd_streaming_contract_matches_run():
    """push/flush streaming emits the same outputs as the batch API (the
    SPMD pipeline's contract, now honored by the fallback engine too)."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=4)
    pipe = MpmdPipeline(stages, params, microbatch=1)
    inputs = np.asarray(
        jax.random.normal(jax.random.key(3), (7, 1, 32, 32, 3)))
    pipe.warmup()
    pipe.reset()
    outs = pipe.push(inputs[:3])
    outs += pipe.push(inputs[3:])
    outs += pipe.flush()
    assert len(outs) == 7
    ref = pipe.run(inputs)
    got = np.stack([np.asarray(o, np.float32) for o in outs])
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    # in-flight window is bounded by pipeline depth during streaming
    pipe.reset()
    pipe.push(inputs[:4], n_real=4)
    assert len(pipe._inflight) <= pipe.num_stages
