"""Multi-process stage-node chain: the reference's execution topology.

Spawns real OS processes (one per stage) wired into a series chain over
framed TCP, streams inputs through, and checks the collected outputs
against the single-program oracle — the end-to-end analogue of deploying
``python node.py`` on N machines plus the dispatcher (reference
src/node.py:126-127, src/dispatcher.py:44-65, test/test.py).
"""

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.runtime.node import run_chain

#: stage-node subprocesses must never touch the (single-client) TPU tunnel
CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


@pytest.mark.slow
def test_three_process_chain_matches_single_program(tiny):
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(1)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    outs = run_chain(stages, params, xs, env=CPU_ENV)
    assert len(outs) == 5
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(
            y, np.asarray(fwd(params, x)), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_in_band_deploy_no_preplaced_files(tiny):
    """Control-plane parity (VERDICT r4 missing #1): nodes boot with NO
    --artifact and receive StableHLO+weights over the socket with an ACK
    handshake, then serve the chain normally."""
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(3)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(4)]
    outs = run_chain(stages, params, xs, env=CPU_ENV, in_band=True)
    assert len(outs) == 4
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(
            y, np.asarray(fwd(params, x)), rtol=2e-4, atol=2e-4)


def test_reweight_swaps_weights_in_place(tiny):
    """Weights-only re-push: a deployed StageProgram installs fresh
    weights without reloading StableHLO, and rejects shape mismatches."""
    from defer_tpu.utils.export import (export_stage_bytes,
                                        load_stage_program,
                                        stage_weight_leaves, weights_blob)
    g, params = tiny
    stages = partition(g, num_stages=2)
    blob = export_stage_bytes(stages[0], params, batch=1)
    prog = load_stage_program(blob)
    x = np.random.default_rng(4).standard_normal((1, 32, 32, 3)) \
        .astype(np.float32)
    y0 = np.asarray(prog(x))
    # re-push scaled weights -> output must change deterministically
    params2 = jax.tree.map(lambda a: a * 1.5, params)
    prog.reweight(weights_blob(stage_weight_leaves(stages[0], params2)))
    y1 = np.asarray(prog(x))
    assert not np.allclose(y0, y1)
    # and pushing the originals back restores the original output exactly
    prog.reweight(weights_blob(stage_weight_leaves(stages[0], params)))
    np.testing.assert_array_equal(np.asarray(prog(x)), y0)
    # wrong shapes are refused loudly
    bad = [np.zeros((2, 2), np.float32)] * prog.manifest["num_weights"]
    with pytest.raises(ValueError, match="re-push"):
        prog.reweight(weights_blob(bad))


@pytest.mark.slow
def test_in_band_reweight_over_socket(tiny):
    """Deploy in-band, stream, then re-push weights over a fresh control
    connection and stream again — redeploy without restart, end to end."""
    import threading
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    g, params = tiny
    stages = partition(g, num_stages=2)
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(2)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    counts = {}

    def serve(i):
        # both streams ride one upstream data connection (END arrives only
        # at dispatcher close); the reweight control connection is handled
        # concurrently mid-stream
        counts[i] = nodes[i].serve()

    threads = [threading.Thread(target=serve, args=(i,), daemon=True)
               for i in range(2)]
    for t in threads:
        t.start()

    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(stages, params, addrs, batch=1)
    rng = np.random.default_rng(5)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(3)]
    out1 = disp.stream(xs)
    st = disp.stats(addrs)  # mid-stream observability
    assert [s["stage"] for s in st] == [0, 1]
    assert all(s["processed"] == 3 and s["reweights"] == 0 for s in st)
    params2 = jax.tree.map(lambda a: a * 0.5, params)
    disp.reweight(stages, params2, addrs)
    out2 = disp.stream(xs)
    st2 = disp.stats(addrs)
    assert all(s["processed"] == 6 and s["reweights"] == 1 for s in st2)
    disp.close()
    for t in threads:
        t.join(timeout=30)
    assert counts == {0: 6, 1: 6}  # 3 + 3 tensors through each node
    fwd = jax.jit(g.apply)
    for x, y1, y2 in zip(xs, out1, out2):
        np.testing.assert_allclose(y1, np.asarray(fwd(params, x)),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(y2, np.asarray(fwd(params2, x)),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_chain_with_lossless_codec(tiny):
    """The first-party C++ LZB codec on every hop (the reference's LZ4
    role, but symmetric) must be bit-transparent end to end."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    rng = np.random.default_rng(2)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(3)]
    raw = run_chain(stages, params, xs, env=CPU_ENV, codec="raw")
    lzb = run_chain(stages, params, xs, env=CPU_ENV, codec="lzb")
    for a, b in zip(raw, lzb):
        np.testing.assert_array_equal(a, b)
