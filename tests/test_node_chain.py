"""Multi-process stage-node chain: the reference's execution topology.

Spawns real OS processes (one per stage) wired into a series chain over
framed TCP, streams inputs through, and checks the collected outputs
against the single-program oracle — the end-to-end analogue of deploying
``python node.py`` on N machines plus the dispatcher (reference
src/node.py:126-127, src/dispatcher.py:44-65, test/test.py).
"""

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.runtime.node import run_chain

#: stage-node subprocesses must never touch the (single-client) TPU tunnel
CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


@pytest.mark.slow
def test_three_process_chain_matches_single_program(tiny):
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(1)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    outs = run_chain(stages, params, xs, env=CPU_ENV)
    assert len(outs) == 5
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(
            y, np.asarray(fwd(params, x)), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_chain_with_lossless_codec(tiny):
    """The first-party C++ LZB codec on every hop (the reference's LZ4
    role, but symmetric) must be bit-transparent end to end."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    rng = np.random.default_rng(2)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(3)]
    raw = run_chain(stages, params, xs, env=CPU_ENV, codec="raw")
    lzb = run_chain(stages, params, xs, env=CPU_ENV, codec="lzb")
    for a, b in zip(raw, lzb):
        np.testing.assert_array_equal(a, b)
