"""Telemetry subsystem: histogram quantiles, registry formats, tracer
span links, cross-process trace propagation through the MPMD chain, and
the satellite fixes (as_dict completeness, codec-cache thread safety,
infer_stream timeout plumbing)."""

import json
import socket
import threading

import numpy as np
import pytest

import jax

from defer_tpu.obs import (LatencyHistogram, MetricsRegistry, Tracer,
                           tracer)


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------

def test_histogram_constant_distribution():
    h = LatencyHistogram()
    for _ in range(100):
        h.record(0.25)
    assert h.count == 100
    assert h.min == h.max == 0.25
    # quantiles of a constant are that constant (clamped to observed range)
    for q in (0.0, 0.5, 0.95, 0.99, 1.0):
        assert h.quantile(q) == 0.25
    p = h.percentiles
    assert p["p50"] == p["p99"] == p["max"] == 0.25


def test_histogram_uniform_quantiles_within_bucket_resolution():
    h = LatencyHistogram()
    for i in range(1, 10001):
        h.record(i / 1000.0)  # uniform on (0, 10]
    # log buckets at 8/octave -> <= ~9% relative error per quantile
    assert h.quantile(0.5) == pytest.approx(5.0, rel=0.1)
    assert h.quantile(0.95) == pytest.approx(9.5, rel=0.1)
    assert h.quantile(0.99) == pytest.approx(9.9, rel=0.1)
    assert h.max == 10.0
    assert h.mean == pytest.approx(5.0005, rel=1e-6)


def test_histogram_merge_matches_combined():
    a, b, c = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    xs = [0.001 * (i + 1) for i in range(50)]
    ys = [0.1 * (i + 1) for i in range(50)]
    for x in xs:
        a.record(x)
        c.record(x)
    for y in ys:
        b.record(y)
        c.record(y)
    a.merge(b)
    assert a.count == c.count == 100
    assert a.sum == pytest.approx(c.sum)
    assert a.min == c.min and a.max == c.max
    for q in (0.1, 0.5, 0.9, 0.99):
        assert a.quantile(q) == pytest.approx(c.quantile(q))


def test_histogram_empty_and_outliers():
    h = LatencyHistogram()
    assert h.quantile(0.5) == 0.0
    assert h.summary() == {"count": 0}
    h.record(0.0)          # clamps into the bottom bucket, keeps exact min
    h.record(float("nan"))  # ignored
    h.record(1e6)           # huge outlier is representable
    assert h.count == 2
    assert h.min == 0.0 and h.max == 1e6


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_get_or_create_and_type_conflict():
    r = MetricsRegistry()
    c = r.counter("a.b")
    assert r.counter("a.b") is c
    c.inc(3)
    c.n += 2
    assert r.counter("a.b").value == 5
    with pytest.raises(TypeError):
        r.gauge("a.b")


def test_registry_snapshot_and_callbacks():
    r = MetricsRegistry()
    r.counter("tx.frames").inc(7)
    r.gauge("depth").set(3.5)
    h = r.histogram("lat_s")
    for v in (0.01, 0.02, 0.04):
        h.record(v)
    state = {"inferences": 42}
    r.register_callback("pipe.inferences", lambda: state["inferences"])
    s = r.snapshot()
    assert s["tx.frames"] == 7
    assert s["depth"] == 3.5
    assert s["lat_s"]["count"] == 3
    assert {"p50", "p95", "p99", "max"} <= set(s["lat_s"])
    assert s["pipe.inferences"] == 42
    state["inferences"] = 43  # callbacks are live
    assert r.snapshot()["pipe.inferences"] == 43
    # snapshot is json-serializable as-is
    json.dumps(s)


def test_registry_prometheus_exposition():
    r = MetricsRegistry()
    r.counter("transport.tx_bytes").inc(1024)
    h = r.histogram("push.latency_s")
    h.record(0.5)
    text = r.exposition()
    assert "# TYPE transport_tx_bytes counter" in text
    assert "transport_tx_bytes 1024" in text
    assert "# TYPE push_latency_s summary" in text
    assert 'push_latency_s{quantile="0.5"}' in text
    assert "push_latency_s_count 1" in text


def test_registry_unregister_prefix():
    r = MetricsRegistry()
    r.counter("p0.a")
    r.counter("p0.b")
    r.counter("p1.a")
    r.unregister("p0.")
    s = r.snapshot()
    assert "p0.a" not in s and "p0.b" not in s and "p1.a" in s


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_tracer_disabled_records_nothing():
    t = Tracer(process="t")
    with t.span("work", {"k": 1}) as s:
        assert s.span_id is None  # shared no-op
    assert t.spans == []


def test_tracer_span_nesting_and_links():
    t = Tracer(process="t", enabled=True)
    tid = t.start_trace()
    with t.span("outer") as outer:
        with t.span("inner"):
            pass
    spans = t.spans
    assert len(spans) == 2
    inner, outer_s = spans  # inner finishes first
    assert inner["name"] == "inner" and outer_s["name"] == "outer"
    assert inner["trace"] == outer_s["trace"] == tid
    assert inner["parent"] == outer_s["span"]
    assert outer_s["parent"] is None
    assert inner["dur_us"] >= 1 and inner["ts_us"] >= outer_s["ts_us"]


def test_tracer_inject_adopt_roundtrip():
    parent = Tracer(process="dispatcher", enabled=True)
    parent.start_trace()
    with parent.span("root"):
        ctx = parent.inject()
    child = Tracer(process="stage0")  # e.g. another process, off
    child.adopt(json.loads(json.dumps(ctx)))  # survives the wire
    assert child.enabled  # adoption turns tracing on remotely
    with child.span("stage0.infer"):
        pass
    (s,) = child.spans
    assert s["trace"] == parent.trace_id
    assert s["parent"] == ctx["span_id"]
    # drain/ingest stitches the remote spans into the parent's export
    parent.ingest(child.drain())
    assert child.spans == []
    names = {x["name"] for x in parent.spans}
    assert names == {"root", "stage0.infer"}


def test_tracer_chrome_export(tmp_path):
    t = Tracer(process="procA", enabled=True)
    with t.span("alpha", {"x": 1}):
        pass
    t.record("beta", 0.0, 0.001)
    path = str(tmp_path / "trace.json")
    t.export_chrome(path)
    doc = json.load(open(path))
    events = doc["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    xs = [e for e in events if e["ph"] == "X"]
    assert metas and metas[0]["args"]["name"] == "procA"
    assert {e["name"] for e in xs} == {"alpha", "beta"}
    for e in xs:
        assert e["dur"] >= 1 and "trace_id" in e["args"]


# ---------------------------------------------------------------------------
# PipelineMetrics as a registry view (satellite: as_dict completeness)
# ---------------------------------------------------------------------------

def test_pipeline_metrics_as_dict_self_describing():
    from defer_tpu.utils.metrics import PipelineMetrics

    m = PipelineMetrics(num_stages=4, microbatch=2)
    m.inferences, m.steps = 6, 5
    d = m.as_dict()
    # the bubble_fraction inputs must be in the export (satellite fix)
    assert d["microbatch"] == 2 and d["steps"] == 5
    assert d["bubble_fraction"] == pytest.approx(1.0 - (6 / 2) / 5)


def test_pipeline_metrics_histograms_and_bind():
    from defer_tpu.obs import MetricsRegistry
    from defer_tpu.utils.metrics import PipelineMetrics

    r = MetricsRegistry()
    m = PipelineMetrics(num_stages=2, microbatch=1)
    prefix = m.bind(registry=r, prefix="pipeX")
    assert prefix == "pipeX"
    m.inferences = 3
    m.push_latency.record(0.010)
    m.push_latency.record(0.020)
    m.record_stage_latency(0, 0.001)
    m.record_stage_latency(1, 0.004)
    d = m.as_dict()
    assert d["push_latency_ms"]["count"] == 2
    assert d["push_latency_ms"]["p50"] == pytest.approx(10.0, rel=0.2)
    assert len(d["stage_latency_percentiles_ms"]) == 2
    # legacy mean view stays in sync
    assert d["stage_latency_ms"][1] == pytest.approx(4.0, rel=0.1)
    # registry snapshot carries the same data (the "view" contract)
    s = r.snapshot()
    assert s["pipeX.inferences"] == 3
    assert s["pipeX.push_latency_s"]["count"] == 2
    assert s["pipeX.stage1.latency_s"]["count"] == 1


def test_spmd_pipeline_populates_registry(monkeypatch):
    """An SPMD deployment publishes push percentiles + per-hop bytes."""
    from defer_tpu import SpmdPipeline, partition, pipeline_mesh
    from defer_tpu.models import resnet_tiny
    from defer_tpu.obs import REGISTRY

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=2)
    xs = np.zeros((2, 1, 32, 32, 3), np.float32)
    pipe.push(xs)
    pipe.flush()
    prefix = pipe.metrics.prefix
    s = REGISTRY.snapshot()
    assert s[f"{prefix}.push_latency_s"]["count"] >= 2
    bph = pipe.metrics.buffer_bytes_per_hop
    # every hop paid bytes_per_hop per executed step
    assert s[f"{prefix}.hop0.bytes"] == pipe.metrics.steps * bph
    assert s[f"{prefix}.hop1.bytes"] == pipe.metrics.steps * bph


# ---------------------------------------------------------------------------
# transport satellites: codec cache thread safety, timeout plumbing
# ---------------------------------------------------------------------------

def test_codec_cache_concurrent_population():
    """Sender and receiver threads fault codecs in concurrently; every
    thread must get a working codec and the cache must hold one instance
    per name (the old unlocked dict could interleave construction)."""
    import defer_tpu.transport.framed as fr

    fr._CODECS.clear()
    names = ["raw", "lzb", "bf8", "bf12", "bf16"]
    got: list = []
    errs: list = []
    start = threading.Barrier(8)

    def worker():
        try:
            start.wait(timeout=5)
            for _ in range(50):
                for n in names:
                    got.append((n, fr._codec(n)))
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs
    by_name: dict = {}
    for n, c in got:
        assert by_name.setdefault(n, c) is c  # one instance per name
    with pytest.raises(ValueError):
        fr._codec("zstd99")


def test_infer_stream_timeout_plumbed():
    """A peer that never drains trips TimeoutError at the caller's
    timeout_s, not the old hardcoded 600 s."""
    from defer_tpu.transport.framed import TensorClient

    a, b = socket.socketpair()
    try:
        c = TensorClient.__new__(TensorClient)
        c._sock = a
        c.timeout_s = 0.2
        with pytest.raises(TimeoutError, match="did not drain"):
            c.infer_stream([np.zeros((1, 4), np.float32)])
        # per-call override beats the instance default
        c.timeout_s = 600.0
        with pytest.raises(TimeoutError):
            c.infer_stream([np.zeros((1, 4), np.float32)], timeout_s=0.2)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# cross-process propagation (satellite: trace id through a 2-proc chain)
# ---------------------------------------------------------------------------

def test_stage_node_adopts_and_dumps_trace_ctx():
    """Unit-level round trip of the K_CTRL trace commands against a live
    StageNode handler (no subprocesses): adopt -> record -> dump."""
    from defer_tpu.runtime.node import StageNode
    from defer_tpu.transport.framed import K_CTRL, recv_frame, send_ctrl

    node = StageNode.__new__(StageNode)
    node.prog = None
    node.next_hop = None
    node.codec = "raw"
    node.processed = 0
    node.reweights = 0
    node.address = ("127.0.0.1", 0)
    node._pending_trace = None

    tr = tracer()
    was_enabled, old_proc = tr.enabled, tr.process
    try:
        a, b = socket.socketpair()
        ctx = {"cmd": "trace", "trace_id": "feedc0defeedc0de",
               "span_id": "abad1deaabad1dea"}
        assert node._handle_ctrl(a, ctx)
        assert node._pending_trace == ctx
        assert tr.enabled and tr.trace_id == "feedc0defeedc0de"
        tr.record("stage?.infer", 0.0, 0.001)
        (s,) = [x for x in tr.spans if x["name"] == "stage?.infer"]
        assert s["trace"] == "feedc0defeedc0de"
        assert s["parent"] == "abad1deaabad1dea"
        # trace_dump replies with (and drains) the recorded spans
        node._handle_ctrl(a, {"cmd": "trace_dump"})
        kind, reply = recv_frame(b)
        assert kind == K_CTRL
        names = [x["name"] for x in reply["spans"]]
        assert "stage?.infer" in names
        a.close()
        b.close()
    finally:
        tr.enabled = was_enabled
        tr.process = old_proc
        tr._remote_parent = None
        tr.clear()


def test_trace_id_survives_two_process_chain():
    """The satellite round trip: a trace id injected at the dispatcher
    rides K_CTRL frames through a 2-process node chain and every stage
    process's spans come back stitched under the dispatcher's root."""
    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.runtime.node import run_chain

    cpu_env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
               "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=2)
    xs = [np.random.default_rng(7).standard_normal((1, 32, 32, 3))
          .astype(np.float32) for _ in range(3)]

    tr = tracer()
    was_enabled, old_proc = tr.enabled, tr.process
    tr.clear()
    try:
        tr.enabled = True
        tr.process = "dispatcher"
        tid = tr.start_trace()
        outs = run_chain(stages, params, xs, env=cpu_env)
        assert len(outs) == 3
        spans = tr.spans
        root = [s for s in spans if s["name"] == "chain.stream"]
        assert len(root) == 1
        # every stage process contributed spans, all under ONE trace id
        for k in range(2):
            stage_spans = [s for s in spans
                           if s["name"] == f"stage{k}.infer"]
            assert len(stage_spans) == 3, \
                f"stage {k}: {[s['name'] for s in spans]}"
            for s in stage_spans:
                assert s["trace"] == tid
                assert s["parent"] == root[0]["span"]
                assert s["proc"] == f"stage{k}"
    finally:
        tr.enabled = was_enabled
        tr.process = old_proc
        tr._remote_parent = None
        tr.clear()
