"""Flight recorder (obs/events.py): ring bounds, wire-schema
roundtrip, cluster-wide merge ordering, and the admission layer's
shed/admit emission."""

import json

import pytest

from defer_tpu.obs.cluster import ClusterView
from defer_tpu.obs.events import (EVENT_KINDS, FlightRecorder,
                                  merge_events, recorder, validate_event)
from defer_tpu.serve import AdmissionController, TenantConfig


# ---------------------------------------------------------------------------
# ring bounds
# ---------------------------------------------------------------------------

def test_ring_drops_oldest_and_counts_losses():
    rec = FlightRecorder(process="p", capacity=8)
    for i in range(13):
        rec.emit("admit", tenant="t", rid=i)
    evs = rec.snapshot()
    assert len(evs) == 8
    assert rec.dropped == 5
    # the OLDEST were evicted: the survivors are seqs 5..12, contiguous
    assert [e["seq"] for e in evs] == list(range(5, 13))
    assert [e["data"]["rid"] for e in evs] == list(range(5, 13))
    # the cursor contract survives eviction: a reader that was at 0
    # sees only what is left, and the seq gap proves the loss
    cursor, batch = rec.events_since(0)
    assert cursor == 13 and len(batch) == 8
    cursor2, batch2 = rec.events_since(cursor)
    assert cursor2 == cursor and batch2 == []
    rec.emit("shed", tenant="t", reason="deadline")
    cursor3, batch3 = rec.events_since(cursor)
    assert len(batch3) == 1 and batch3[0]["kind"] == "shed"


def test_ring_limit_paginates_losslessly():
    """A limited read returns the OLDEST events and a partial cursor,
    so a backlog drains across successive reads with nothing skipped."""
    rec = FlightRecorder(process="p", capacity=64)
    for i in range(10):
        rec.emit("admit", rid=i)
    cursor, batch = rec.events_since(0, limit=3)
    assert [e["data"]["rid"] for e in batch] == [0, 1, 2]
    cursor, batch = rec.events_since(cursor, limit=3)
    assert [e["data"]["rid"] for e in batch] == [3, 4, 5]
    cursor, batch = rec.events_since(cursor, limit=100)
    assert [e["data"]["rid"] for e in batch] == [6, 7, 8, 9]
    assert rec.events_since(cursor, limit=3)[1] == []


# ---------------------------------------------------------------------------
# wire schema
# ---------------------------------------------------------------------------

def test_event_wire_roundtrip_and_validation():
    rec = FlightRecorder(process="stage1")
    ev = rec.emit("tier", hop="stage1", tier="shm", fallback=False)
    wire = json.loads(json.dumps(ev))      # the obs_push trip
    assert validate_event(wire) == ev
    with pytest.raises(ValueError, match="unknown event kind"):
        validate_event({**wire, "kind": "nope"})
    with pytest.raises(ValueError, match="exactly keys"):
        validate_event({k: v for k, v in wire.items() if k != "proc"})
    with pytest.raises(ValueError, match="seq"):
        validate_event({**wire, "seq": -1})
    with pytest.raises(ValueError, match="unknown event kind"):
        rec.emit("not_a_kind")
    # every documented kind is emittable
    for kind in EVENT_KINDS:
        validate_event(json.loads(json.dumps(
            FlightRecorder(process="x").emit(kind))))


# ---------------------------------------------------------------------------
# cluster-wide merge
# ---------------------------------------------------------------------------

def _push_with_events(stage, events, dropped=0):
    return {"node": {"stage": stage, "replica": None},
            "processed": 0,
            "events": {"dropped": dropped, "events": events}}


def test_cluster_view_merges_cross_process_events_in_order():
    """Two processes' event streams merge by aligned timestamp with
    per-process seq as the tie break — one process's events can never
    reorder against each other."""
    a = FlightRecorder(process="stage0")
    b = FlightRecorder(process="stage1")
    e0 = a.emit("stream_begin", hop="stage0")
    e1 = b.emit("stream_begin", hop="stage1")
    e2 = a.emit("stream_end", hop="stage0", n=4)
    # fabricate aligned timestamps so the intended order is unambiguous
    e0["t_us"], e1["t_us"], e2["t_us"] = 100, 200, 300
    view = ClusterView()
    view.ingest(_push_with_events(0, [e0, e2], dropped=0), "a:1")
    view.ingest(_push_with_events(1, [e1], dropped=2), "b:2")
    merged = view.events(include_local=False)
    assert [e["t_us"] for e in merged] == [100, 200, 300]
    assert [e["proc"] for e in merged] == ["stage0", "stage1", "stage0"]
    assert view.events_dropped == 2
    # same-instant burst from ONE process stays in seq order
    e3 = b.emit("straggler", stage=1, reason="slow")
    e4 = b.emit("replan", moved=True)
    e3["t_us"] = e4["t_us"] = 400
    assert [e["seq"] for e in merge_events([e4, e3])
            if e["t_us"] == 400] == [e3["seq"], e4["seq"]]
    # take_events drains incrementally (the monitor's read)
    assert len(view.take_events()) == 3
    assert view.take_events() == []


# ---------------------------------------------------------------------------
# emission sites
# ---------------------------------------------------------------------------

def test_admission_emits_shed_and_admit_events():
    rec = recorder()
    before = rec.cursor()
    ctl = AdmissionController(service_s=lambda: 0.2)
    ctl.configure(TenantConfig("evt_t", deadline_ms=100.0))
    assert not ctl.admit("evt_t", object()).admitted
    ctl2 = AdmissionController(service_s=lambda: 0.0)
    ctl2.configure(TenantConfig("evt_t2"))
    assert ctl2.admit("evt_t2", object()).admitted
    _, evs = rec.events_since(before)
    kinds = {(e["kind"], e["data"].get("tenant")) for e in evs}
    assert ("shed", "evt_t") in kinds
    assert ("admit", "evt_t2") in kinds
    shed = next(e for e in evs if e["kind"] == "shed")
    assert shed["data"]["reason"] == "deadline"
    assert shed["data"]["predicted_ms"] > 100.0
