"""Live observability plane: tracer span cap + cursors, clock
alignment, Gauge inc/dec, Prometheus exposition hardening + HTTP
endpoint, ClusterView aggregation, straggler detection and its replan
suggestion, and the obs_push path through a real (in-process) 3-node
chain."""

import json
import re
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

import jax

from defer_tpu.obs import (REGISTRY, ClusterView, Gauge, MetricsRegistry,
                           StragglerDetector, Tracer, start_prom_server,
                           tracer)
from defer_tpu.obs.cluster import (align_clock, estimate_clock_offset,
                                   expected_stage_ms)


# ---------------------------------------------------------------------------
# tracer: span-buffer cap + incremental cursors + anchor shift
# ---------------------------------------------------------------------------

def test_tracer_span_cap_and_dropped_counter():
    """A long traced stream must not grow memory without bound: past
    max_spans the OLDEST spans are evicted and counted (satellite
    regression test)."""
    dropped0 = REGISTRY.counter("trace.dropped_spans").value
    t = Tracer(process="t", enabled=True, max_spans=10)
    for i in range(25):
        t.record(f"s{i}", 0.0, 0.001)
    assert len(t.spans) == 10
    assert t.dropped == 15
    # the NEWEST spans survive (a live monitor wants recent history)
    assert [s["name"] for s in t.spans] == [f"s{i}" for i in range(15, 25)]
    assert REGISTRY.counter("trace.dropped_spans").value == dropped0 + 15
    # ingest respects the cap too
    t.ingest([{"name": f"x{i}", "ts_us": 0, "dur_us": 1} for i in range(8)])
    assert len(t.spans) == 10 and t.dropped == 15 + 8


def test_tracer_span_cursor_batches():
    """spans_since reads incrementally WITHOUT draining — pushes and the
    end-of-stream trace_dump must not steal from each other."""
    t = Tracer(process="t", enabled=True)
    c0 = t.span_cursor()
    t.record("a", 0.0, 0.001)
    t.record("b", 0.0, 0.001)
    c1, batch = t.spans_since(c0)
    assert [s["name"] for s in batch] == ["a", "b"]
    c2, batch = t.spans_since(c1)
    assert batch == [] and c2 == c1
    t.record("c", 0.0, 0.001)
    _, batch = t.spans_since(c1)
    assert [s["name"] for s in batch] == ["c"]
    # limit keeps the newest of an oversized batch
    for i in range(5):
        t.record(f"d{i}", 0.0, 0.001)
    _, batch = t.spans_since(c2, limit=2)
    assert [s["name"] for s in batch] == ["d3", "d4"]
    # a drain moves the base; an old cursor stays valid (returns only
    # what still exists)
    assert len(t.drain()) == 8
    t.record("e", 0.0, 0.001)
    _, batch = t.spans_since(c0)
    assert [s["name"] for s in batch] == ["e"]


def test_tracer_shift_wall_anchor_moves_buffered_spans():
    t = Tracer(process="t", enabled=True)
    t.record("a", t._mono0, 0.001)
    ts0 = t.spans[0]["ts_us"]
    now0 = t.now_us()
    t.shift_wall_anchor(123_456)
    assert t.spans[0]["ts_us"] == ts0 + 123_456
    assert t.now_us() - now0 >= 123_456


def test_tracer_buffer_ops_survive_concurrent_recording():
    """Regression: shift_wall_anchor / spans_since / drain iterate the
    span buffer while hot-path threads append — a live-deque iteration
    would raise RuntimeError and kill the connection worker applying a
    clock_adjust mid-stream."""
    t = Tracer(process="t", enabled=True)
    stop = threading.Event()
    errs: list = []

    def recorder():
        try:
            while not stop.is_set():
                t.record("hot", 0.0, 1e-6)
        except BaseException as e:  # noqa: BLE001 — the regression
            errs.append(e)

    th = threading.Thread(target=recorder, daemon=True)
    th.start()
    try:
        cursor = 0
        deadline = time.monotonic() + 0.5
        drained = 0
        while time.monotonic() < deadline:
            t.shift_wall_anchor(7)
            cursor, batch = t.spans_since(cursor, limit=64)
            t.chrome_events()
            drained += len(t.drain())
    finally:
        stop.set()
        th.join(timeout=10)
    assert not errs, errs
    assert drained > 0


# ---------------------------------------------------------------------------
# gauge inc/dec + watermark (satellite)
# ---------------------------------------------------------------------------

def test_gauge_inc_dec_and_watermark():
    g = Gauge()
    g.inc()
    g.inc(2)
    assert g.value == 3
    g.dec()
    assert g.value == 2
    # watermark: peak since last take, resetting to current
    assert g.take_watermark() == 3
    assert g.take_watermark() == 2
    g.set(7)
    g.set(1)
    assert g.take_watermark() == 7


# ---------------------------------------------------------------------------
# Prometheus exposition hardening (satellite)
# ---------------------------------------------------------------------------

#: promtool-style line shapes for the text format
_HELP_RE = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*$")
_TYPE_RE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary|histogram)$")
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*")*\})?'
    r' -?[0-9.eE+naif]+$')


def test_exposition_is_promtool_valid_with_hostile_names():
    r = MetricsRegistry()
    r.counter("transport.tx_frames").inc(7)
    r.counter("1starts.with-digit").inc(1)
    r.counter("weird name/with spaces").inc(2)
    r.gauge("node.rx_queue_depth").set(3)
    h = r.histogram("push.latency_s")
    for v in (0.01, 0.02):
        h.record(v)
    r.register_callback("cb.metric", lambda: 1.5)
    text = r.exposition()
    assert text.endswith("\n")
    names_with_help = set()
    for line in text.strip().split("\n"):
        if line.startswith("# HELP"):
            assert _HELP_RE.match(line), line
            names_with_help.add(line.split()[2])
        elif line.startswith("# TYPE"):
            assert _TYPE_RE.match(line), line
            # every family announced a HELP line first
            assert line.split()[2] in names_with_help, line
        else:
            assert _SAMPLE_RE.match(line), line
    # sanitized names: legal charset, never digit-first
    assert "_1starts_with_digit 1" in text
    assert "weird_name_with_spaces 2" in text
    # quantile labels survive as proper label values
    assert 'push_latency_s{quantile="0.5"}' in text


def test_prom_http_endpoint_serves_exposition():
    r = MetricsRegistry()
    r.counter("live.requests").inc(3)
    srv = start_prom_server(0, registry=r)
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "# TYPE live_requests counter" in body
        assert "live_requests 3" in body
    finally:
        srv.shutdown()
        srv.server_close()


def test_obs_reporter_dead_thread_survives_is_alive_check():
    """Regression: ``threading.Thread`` calls ``self._stop()`` as a
    METHOD while checking a dead thread's ``is_alive()`` — an Event
    attribute shadowing it broke every re-subscription after the first
    subscriber disconnected."""
    from defer_tpu.obs import ObsReporter
    from defer_tpu.transport.framed import K_CTRL, recv_frame

    class Src:
        def obs_snapshot(self, *, cursor, include_spans, span_limit):
            return {"node": {"stage": 0}, "processed": 0}, cursor

    a, b = socket.socketpair()
    rep = ObsReporter(Src(), a, interval_s=0.02)
    rep.start()
    kind, msg = recv_frame(b)
    assert kind == K_CTRL and msg["cmd"] == "obs_push"
    a.close()
    b.close()
    rep.join(timeout=10)
    assert rep.is_alive() is False  # raised TypeError before the fix
    rep.stop()  # idempotent


class _FakeChan:
    """take_watermark() double with a settable peak."""

    def __init__(self):
        self.hi = 0

    def take_watermark(self):
        h, self.hi = self.hi, 0
        return h

    def qsize(self):
        return 0

    class _H:  # noqa: D106 — enc/dec histogram double
        @staticmethod
        def summary():
            return {"count": 0}

    enc = dec = _H()


def _node_stub():
    """A ``__new__``-built StageNode with just enough attributes for
    obs_snapshot / the splitter."""
    from defer_tpu.runtime.node import StageNode

    node = StageNode.__new__(StageNode)
    node._reporters = []
    node.prog = None
    node.address = ("127.0.0.1", 0)
    node.codec = "raw"
    node.processed = 0
    node.reweights = 0
    node._merge = None
    return node


def test_watermark_split_per_subscriber():
    """The PR 5 known issue, fixed: reset-on-read watermarks are split
    per subscriber — each sees the true peak since ITS own last read,
    so a shedding loop and a human monitor can watch the same node."""
    from defer_tpu.obs.report import WatermarkSplit

    split = WatermarkSplit()
    chan = _FakeChan()
    split.register(1)
    split.register(2)
    chan.hi = 7
    assert split.take(1, "rx", chan) == 7   # 1 drains the burst...
    assert split.take(2, "rx", chan) == 7   # ...and 2 STILL sees it
    assert split.take(1, "rx", chan) == 0   # nothing new since 1's read
    chan.hi = 3
    assert split.take(2, "rx", chan) == 3
    chan.hi = 5
    # an unregistered caller gets the raw fold without draining anyone
    assert split.take(None, "rx", chan) == 5
    assert split.take(1, "rx", chan) == 5
    split.unregister(2)
    assert split.subscribers() == 1
    assert split.take(None, "rx", None) == 0  # dead channel: quiet zero


def test_stage_node_watermarks_split_across_two_subscribers():
    """Node-level: two concurrent obs_snapshot subscribers both see the
    same queue burst instead of whoever-reads-first stealing it."""
    node = _node_stub()
    rx = _FakeChan()
    node._live_rx = rx
    node._live_tx = None
    node.obs_register(101)
    node.obs_register(202)
    rx.hi = 9
    p1, _, _ = node.obs_snapshot(subscriber=101, include_spans=False)
    p2, _, _ = node.obs_snapshot(subscriber=202, include_spans=False)
    assert p1["queues"]["rx_hi"] == 9
    assert p2["queues"]["rx_hi"] == 9, \
        "the second subscriber lost the burst to the first's reset"
    p1b, _, _ = node.obs_snapshot(subscriber=101, include_spans=False)
    assert p1b["queues"]["rx_hi"] == 0
    node.obs_unregister(101)
    node.obs_unregister(202)


def test_obs_reporter_registers_with_watermark_splitter():
    """An ObsReporter subscription registers its id with the source's
    splitter and unregisters when the subscriber disconnects."""
    from defer_tpu.obs import ObsReporter
    from defer_tpu.transport.framed import K_CTRL, recv_frame

    node = _node_stub()
    a, b = socket.socketpair()
    rep = ObsReporter(node, a, interval_s=0.02, spans=False)
    rep.start()
    kind, msg = recv_frame(b)
    assert kind == K_CTRL and msg["cmd"] == "obs_push"
    assert node._wm().subscribers() == 1
    a.close()
    b.close()
    rep.join(timeout=10)
    assert node._wm().subscribers() == 0, \
        "a dead subscription must unregister from the splitter"


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------

def _probe_responder(sock, remote: Tracer):
    """Answer clock_probe/clock_adjust like a StageNode would, against
    ``remote``'s (possibly skewed) timeline."""
    from defer_tpu.transport.framed import (K_CTRL, K_END, recv_frame,
                                            send_ack, send_ctrl)
    while True:
        kind, msg = recv_frame(sock)
        if kind == K_END:
            return
        assert kind == K_CTRL
        if msg["cmd"] == "clock_probe":
            send_ctrl(sock, {"cmd": "clock_probe_reply",
                             "t_us": remote.now_us(),
                             "echo": msg.get("echo")})
        elif msg["cmd"] == "clock_adjust":
            remote.shift_wall_anchor(int(msg["offset_us"]))
            send_ack(sock)


def test_clock_offset_estimator_with_injected_skew():
    """The min-RTT ping-pong estimator recovers a known injected skew
    (satellite: clock-offset unit test)."""
    from defer_tpu.transport.framed import send_end

    local = Tracer(process="disp")
    remote = Tracer(process="node")
    skew = 250_000  # 250 ms
    remote.shift_wall_anchor(skew)
    a, b = socket.socketpair()
    t = threading.Thread(target=_probe_responder, args=(b, remote),
                         daemon=True)
    t.start()
    try:
        # residual anchor error: the two tracers sampled their wall/mono
        # anchors at slightly different instants — sub-ms on one host
        est = estimate_clock_offset(a, rounds=8, local=local)
        assert est["offset_us"] == pytest.approx(skew, abs=5_000)
        assert est["rtt_us"] >= 0
        # align_clock ships the correction: afterwards the two timelines
        # agree within the estimator's own error bound
        align_clock(a, rounds=8, local=local)
        assert abs(remote.now_us() - local.now_us()) < 5_000
        send_end(a)
        t.join(timeout=10)
    finally:
        a.close()
        b.close()


def test_stage_node_clock_ctrl_roundtrip():
    """StageNode answers clock_probe with its tracer timeline and
    applies clock_adjust to the anchor (ACKed)."""
    from defer_tpu.runtime.node import StageNode
    from defer_tpu.transport.framed import (K_ACK, K_CTRL, recv_frame)

    node = StageNode.__new__(StageNode)
    node.prog = None
    node.codec = "raw"
    node.processed = 0
    node.reweights = 0
    node.address = ("127.0.0.1", 0)
    node._pending_trace = None

    tr = tracer()
    wall0 = tr._wall0_us
    a, b = socket.socketpair()
    try:
        before = tr.now_us()
        assert node._handle_ctrl(a, {"cmd": "clock_probe", "echo": 3})
        kind, reply = recv_frame(b)
        assert kind == K_CTRL and reply["echo"] == 3
        assert reply["t_us"] >= before
        assert node._handle_ctrl(a, {"cmd": "clock_adjust",
                                     "offset_us": -777})
        kind, _ = recv_frame(b)
        assert kind == K_ACK
        assert tr._wall0_us == wall0 - 777
    finally:
        tr._wall0_us = wall0
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# ClusterView aggregation + straggler detection (synthetic pushes)
# ---------------------------------------------------------------------------

def _push(stage, *, processed, infer_ms=0.3, dec_ms=0.0, enc_ms=0.0,
          rx_hi=0, tx_hi=0, replica=None, depth=8):
    def summ(ms):
        if ms <= 0:
            return {"count": 0}
        return {"count": 10, "p50": ms / 1e3, "p95": ms / 1e3,
                "p99": ms / 1e3, "mean": ms / 1e3}
    return {"cmd": "obs_push",
            "node": {"stage": stage, "replica": replica, "fan_in": 1,
                     "name": f"stage{stage}", "port": 5000 + stage},
            "processed": processed,
            "counters": {"tx_frames": processed, "tx_bytes": processed * 100,
                         "rx_frames": processed, "rx_bytes": processed * 100},
            "queues": {"rx_depth": depth, "tx_depth": depth, "rx": 0,
                       "tx": 0, "rx_hi": rx_hi, "tx_hi": tx_hi,
                       "inflight": 0, "merge": 0},
            "latency": {"infer_s": summ(infer_ms), "decode_s": summ(dec_ms),
                        "encode_s": summ(enc_ms), "rx_s": {"count": 0},
                        "tx_s": {"count": 0}},
            "trace": {"dropped": 0}}


def test_cluster_view_rows_rates_and_timing_bottleneck():
    view = ClusterView()
    for i in range(3):
        view.ingest(_push(0, processed=10 * (i + 1)), "a:1")
        view.ingest(_push(1, processed=10 * (i + 1), dec_ms=12.0), "a:2")
        view.ingest(_push(2, processed=10 * (i + 1)), "a:3")
        time.sleep(0.02)
    rows = view.rows()
    assert [r["stage"] for r in rows] == [0, 1, 2]
    assert all(r["processed"] == 30 for r in rows)
    assert all(r["pushes"] == 3 for r in rows)
    # delta-rate over the push window is positive and sane
    assert all(r["throughput_per_s"] > 0 for r in rows)
    # the decode-bound stage dominates the service estimate
    assert rows[1]["service_ms"] == pytest.approx(12.0, rel=0.01)
    assert view.bottleneck() == 1
    assert view.stage_service_ms()[1] == pytest.approx(12.0, rel=0.01)
    # stats_rows is replan-consumable (stage + infer summary)
    srows = view.stats_rows()
    assert {r["stage"] for r in srows} == {0, 1, 2}
    assert all(r["infer_latency_s"]["count"] for r in srows)


def test_cluster_view_backpressure_edge_fallback():
    """With flat service times (a wire-bound hop: no CPU histogram sees
    it), the saturation edge names the most-downstream stage the
    backpressure points at."""
    view = ClusterView()
    for i in range(2):
        # stage0 tx saturated (cannot drain into stage1), stage1 clear,
        # stage2 starved: the edge stops at stage 1
        view.ingest(_push(0, processed=10 * (i + 1), tx_hi=8), "a:1")
        view.ingest(_push(1, processed=10 * (i + 1)), "a:2")
        view.ingest(_push(2, processed=10 * (i + 1)), "a:3")
    assert view.bottleneck() == 1


def test_straggler_detector_sustained_slow_and_stalled():
    view = ClusterView()
    # interval 1: everything nominal
    for s in range(3):
        view.ingest(_push(s, processed=10), f"a:{s}")
    det = StragglerDetector([0.3, 0.3, 0.3], factor=1.5, sustain=2)
    assert det.observe(view) == []  # nothing sustained yet
    # intervals 2..3: stage1 turns slow, stage2 stalls
    for i in range(2, 4):
        view.ingest(_push(0, processed=10 * i), "a:0")
        view.ingest(_push(1, processed=10 * i, dec_ms=9.0), "a:1")
        view.ingest(_push(2, processed=10), "a:2")
    flags = {f.stage: f for f in det.observe(view)}
    assert flags[1].reason == "slow"
    assert flags[1].intervals == 2
    assert flags[1].measured_ms == pytest.approx(9.0, rel=0.01)
    assert flags[1].ratio == pytest.approx(30.0, rel=0.01)
    assert flags[2].reason == "stalled"
    assert 0 not in flags  # the healthy stage stays unflagged


def test_straggler_suggest_names_the_slow_stage():
    """The replan suggestion is driven by the live SERVICE estimate, so
    a codec-bound straggler (invisible to infer-only latency) still
    gets the largest correction."""
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel, evaluate_cuts

    b = GraphBuilder("3stage")
    x = b.input((16,))
    x = b.add(ops.Dense(16), x, name="n0")
    x = b.add(ops.Dense(16), x, name="n1")
    x = b.add(ops.Dense(16), x, name="n2")
    g = b.build()
    cm = StageCostModel(g, gen="v4", link_bw_s=1e9,
                        node_costs={"n0": 3e-4, "n1": 3e-4, "n2": 3e-4})
    plan = evaluate_cuts(g, ["n0", "n1"], cm)
    exp = expected_stage_ms(plan)
    assert len(exp) == 3

    view = ClusterView()
    for i in range(1, 4):
        view.ingest(_push(0, processed=8 * i, infer_ms=0.3), "a:0")
        view.ingest(_push(1, processed=8 * i, infer_ms=0.3, enc_ms=10.0),
                    "a:1")
        view.ingest(_push(2, processed=8 * i, infer_ms=0.3), "a:2")
    det = StragglerDetector(exp, factor=1.5, sustain=2)
    flags = det.observe(view)
    assert [f.stage for f in flags] == [1]
    sugg = det.suggest(view, g, plan, cm)
    corr = sugg.corrections
    assert max(corr, key=lambda k: corr[k]) == 1
    assert corr[1] > 10 * max(corr[0], corr[2])
    # the result serializes (what monitor --json prints)
    json.dumps(sugg.to_json())


# ---------------------------------------------------------------------------
# the obs_push path through a live (in-process) 3-node chain
# ---------------------------------------------------------------------------

def test_obs_push_three_node_chain_converges_with_stats():
    """Satellite: a 3-node chain test asserting the ClusterView's
    push-derived model converges to the nodes' own ``stats`` replies,
    plus waterfall sampling producing queue-wait spans keyed on the
    shared wire sequence."""
    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=3)
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(3)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()

    tr = tracer()
    was_enabled, old_proc = tr.enabled, tr.process
    tr.clear()
    try:
        tr.enabled = True
        tr.process = "dispatcher"
        tr.start_trace()
        disp = ChainDispatcher(addrs[0], codec="raw",
                               trace_sample_every=4)
        xs = [np.random.default_rng(0).standard_normal((2, 32, 32, 3))
              .astype(np.float32) for _ in range(16)]
        view = None
        try:
            # 15 ms per side: stage 1's service must dominate even when
            # 1-core scheduling inflates the other stages' infer p50s
            # (tracing + three nodes share this host's single core)
            disp.deploy(stages, params, addrs, batch=2,
                        codecs=["dsleep15+raw", "esleep15+raw", "raw"])
            offsets = disp.align_clocks(addrs)
            assert set(offsets) == set(addrs)
            disp.stream(xs[:2])  # warm: compile transients
            view = disp.watch(addrs, interval_ms=60)
            outs = disp.stream(xs)
            assert len(outs) == 16
            stats = disp.stats(addrs)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                rows = view.rows()
                if len(rows) == 3 and all(r["pushes"] >= 2 and
                                          r["processed"] == 18
                                          for r in rows):
                    break
                time.sleep(0.05)
            rows = view.rows()
            # CONVERGENCE: the push-derived model matches the stats
            # replies — counts exactly, percentiles from the same
            # per-node histogram
            by_stage = {s["stage"]: s for s in stats
                        if s.get("stage") is not None}
            assert len(rows) == 3
            for r in rows:
                s = by_stage[r["stage"]]
                assert r["processed"] == s["processed"]
                assert r["infer_ms"]["p50"] == pytest.approx(
                    s["infer_latency_s"]["p50"] * 1e3, rel=1e-6)
            # the decode/encode-delayed stage is the live bottleneck
            assert view.bottleneck() == 1
            disp.collect_trace(addrs)
        finally:
            if view is not None:
                view.close()
            disp.close()
        for t in threads:
            t.join(timeout=60)
        spans = tr.spans
        names = {s["name"] for s in spans}
        # waterfall sampling: queue-wait spans exist for sampled frames
        assert any(n.endswith(".rx_wait") for n in names), sorted(names)
        assert any(n.endswith(".tx_wait") for n in names), sorted(names)
        # per-frame spans were SAMPLED: only multiples of 4 of the
        # CONTINUOUS wire sequence (2 warm + 16 timed frames = seqs
        # 0..17; sampling never reuses a seq across stream() calls)
        stage_infer_seqs = {s["args"]["seq"] for s in spans
                            if s["name"].endswith(".infer")
                            and s["name"].startswith("stage")}
        assert stage_infer_seqs == {0, 4, 8, 12, 16}, stage_infer_seqs
    finally:
        tr.enabled = was_enabled
        tr.process = old_proc
        tr._remote_parent = None
        tr.clear()


def test_measured_stage_seconds_source_forms():
    """The direct {stage: seconds} mapping passes through; an
    all-numeric registry snapshot (counters/gauges, dotted keys) must
    fall through to the pattern search and yield {} — not crash
    (regression)."""
    from defer_tpu.plan import measured_stage_seconds

    assert measured_stage_seconds({0: 0.01, "1": 0.02}) \
        == {0: 0.01, 1: 0.02}
    assert measured_stage_seconds(
        {"transport.tx_frames": 5, "node.inflight": 1.0}) == {}


# ---------------------------------------------------------------------------
# plan_from_json round trip (monitor --plan input)
# ---------------------------------------------------------------------------

def test_plan_from_json_roundtrip():
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import (StageCostModel, plan_from_json, solve,
                                solve_replicated)

    b = GraphBuilder("rt")
    x = b.input((8,))
    for i in range(4):
        x = b.add(ops.Dense(8), x, name=f"n{i}")
    g = b.build()
    cm = StageCostModel(g, gen="v4", link_bw_s=1e9,
                        node_costs={f"n{i}": 1e-4 * (i + 1)
                                    for i in range(4)})
    plan = solve(g, 2, cm)
    rt = plan_from_json(json.loads(json.dumps(plan.to_json())))
    assert rt.cuts == plan.cuts
    assert rt.num_stages == plan.num_stages
    assert rt.bottleneck_s == pytest.approx(plan.bottleneck_s, rel=1e-4)
    assert rt.bottleneck_stage == plan.bottleneck_stage
    assert expected_stage_ms(rt) == pytest.approx(
        expected_stage_ms(plan), rel=1e-4)
    # replicated plans round-trip their replica counts too
    rp = solve_replicated(g, cm, num_nodes=4)
    rrt = plan_from_json(json.loads(json.dumps(rp.to_json())))
    assert getattr(rrt, "replicas", None) == rp.replicas
    assert expected_stage_ms(rrt) == pytest.approx(
        expected_stage_ms(rp), rel=1e-4)
    # a whole `plan --json` document (plan nested under "plan") works
    assert plan_from_json({"plan": plan.to_json()}).cuts == plan.cuts
