"""Graph optimization passes: BatchNorm folding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu import SpmdPipeline, partition, pipeline_mesh
from defer_tpu.graph.optimize import fold_batchnorm
from defer_tpu.graph.ops import BatchNorm
from defer_tpu.models import mobilenet_tiny, resnet_tiny


def _randomized_bn_params(graph, params, seed):
    """Non-trivial running stats so folding is actually exercised."""
    rng = np.random.default_rng(seed)
    out = dict(params)
    for name, node in graph.nodes.items():
        if isinstance(node.op, BatchNorm):
            c = np.shape(params[name]["mean"])[0]
            out[name] = {
                "scale": jnp.asarray(rng.uniform(0.5, 1.5, c), jnp.float32),
                "bias": jnp.asarray(rng.normal(0, 0.2, c), jnp.float32),
                "mean": jnp.asarray(rng.normal(0, 0.5, c), jnp.float32),
                "var": jnp.asarray(rng.uniform(0.5, 2.0, c), jnp.float32),
            }
    return out


@pytest.mark.parametrize("model_fn", [resnet_tiny, mobilenet_tiny])
def test_fold_batchnorm_preserves_outputs(model_fn):
    graph = model_fn()
    params = _randomized_bn_params(graph, graph.init(jax.random.key(0)), 1)
    folded_graph, folded_params, n = fold_batchnorm(graph, params)
    assert n > 0
    n_bn = sum(isinstance(nd.op, BatchNorm)
               for nd in folded_graph.nodes.values())
    n_bn_orig = sum(isinstance(nd.op, BatchNorm)
                    for nd in graph.nodes.values())
    assert n_bn == n_bn_orig - n
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal(
            (2,) + graph.input_spec.shape), jnp.float32)
    ref = np.asarray(graph.apply(params, x))
    got = np.asarray(folded_graph.apply(folded_params, x))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # originals untouched
    assert any(isinstance(nd.op, BatchNorm) for nd in graph.nodes.values())


def test_folded_graph_runs_in_pipeline():
    graph = resnet_tiny()
    params = _randomized_bn_params(graph, graph.init(jax.random.key(3)), 4)
    fg, fp, n = fold_batchnorm(graph, params)
    assert n > 0
    pipe = SpmdPipeline(partition(fg, num_stages=4), fp,
                        mesh=pipeline_mesh(4), microbatch=1, chunk=4)
    x = np.random.default_rng(5).standard_normal(
        (3, 1) + graph.input_spec.shape).astype(np.float32)
    got = pipe.run(x)
    ref = np.stack([np.asarray(graph.apply(params, xi)) for xi in x])
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_fold_noop_without_bn():
    from defer_tpu.models import gpt_tiny
    g = gpt_tiny()
    p = g.init(jax.random.key(0))
    g2, p2, n = fold_batchnorm(g, p)
    assert n == 0 and g2 is g and p2 is p
