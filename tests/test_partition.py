"""Partitioner tests: the core invariant is stitched-stages ≡ full model
(what the reference's construct_model implicitly guarantees,
reference src/dag_util.py:27-31)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.graph.analysis import valid_cut_points


def _compose(stages, params, x):
    y = x
    for s in stages:
        y = s.fn(s.select_params(params), y)
    return y


def test_stage_structure():
    g = resnet_tiny()
    cuts = ["add", "add_2"]
    stages = partition(g, cuts)
    assert len(stages) == 3
    assert stages[0].input_name == g.input_name
    assert stages[0].output_name == "add"
    assert stages[-1].output_name == g.output_name
    # every graph node appears in exactly one stage
    all_nodes = [n for s in stages for n in s.node_names]
    assert sorted(all_nodes) == sorted(g.topo_order)


def test_partition_equivalence_resnet_tiny():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    full = g.apply(params, x)
    for cuts in (["add_1"], ["add", "add_1", "add_2"]):
        stitched = _compose(partition(g, cuts), params, x)
        np.testing.assert_allclose(np.asarray(full), np.asarray(stitched),
                                   rtol=2e-5, atol=2e-5)


def test_auto_partition_equivalence():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 32, 32, 3))
    full = g.apply(params, x)
    stages = partition(g, num_stages=4)
    assert len(stages) == 4
    stitched = _compose(stages, params, x)
    np.testing.assert_allclose(np.asarray(full), np.asarray(stitched),
                               rtol=2e-5, atol=2e-5)


def test_invalid_cut_rejected():
    """Non-articulation cuts must fail loudly (the reference silently
    requires single-tensor cuts — SURVEY.md §3.5)."""
    g = resnet_tiny()
    valid = set(valid_cut_points(g))
    interior = next(n for n in g.topo_order
                    if n not in valid and n != g.output_name)
    with pytest.raises(ValueError, match="single-tensor"):
        partition(g, [interior])
    with pytest.raises(ValueError, match="not a node"):
        partition(g, ["nope"])
    with pytest.raises(ValueError, match="topological"):
        partition(g, ["add_2", "add"])
