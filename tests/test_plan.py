"""Planner tests: DP optimality vs brute force, bisect agreement, codec
selection, telemetry replan, and the CLI's machine-readable surface."""

import json
import random

import pytest

from defer_tpu import GraphBuilder, partition
from defer_tpu.graph import ops
from defer_tpu.graph.analysis import (auto_cut_points, max_activation_bytes,
                                      max_activation_elems,
                                      valid_cut_points)
from defer_tpu.plan import (CodecSpec, StageCostModel, brute_force,
                            brute_force_replicated, evaluate_cuts,
                            measured_stage_seconds, replan, solve,
                            solve_replicated, sweep_nodes, sweep_stages)


def dense_chain(widths, name="chain", in_width=8):
    b = GraphBuilder(name)
    x = b.input((in_width,))
    for i, w in enumerate(widths):
        x = b.add(ops.Dense(w), x, name=f"fc{i}")
    return b.build()


def random_graph(rng: random.Random, idx: int):
    """Random chain with occasional diamonds (invalid interior cuts), at
    most ~12 valid cut points."""
    b = GraphBuilder(f"rand{idx}")
    x = b.input((rng.choice([2, 4, 8, 16]),))
    n = rng.randint(3, 9)
    for i in range(n):
        w = rng.choice([2, 4, 8, 32, 128])
        if rng.random() < 0.25:
            l = b.add(ops.Dense(w), x, name=f"l{i}")
            r = b.add(ops.Dense(w), x, name=f"r{i}")
            x = b.add(ops.Add(), [l, r], name=f"m{i}")
        else:
            x = b.add(ops.Dense(w), x, name=f"d{i}")
    return b.build()


# -- solver optimality -------------------------------------------------------


def test_dp_matches_brute_force_property():
    """The DP must equal exhaustive enumeration's bottleneck on every
    random small graph, for every feasible stage count — and the binary-
    search variant must agree with both."""
    rng = random.Random(7)
    checked = 0
    for t in range(14):
        g = random_graph(rng, t)
        C = len(valid_cut_points(g))
        if C == 0:
            continue
        cm = StageCostModel(
            g, batch=rng.choice([1, 4]), gen="v4",
            link_bw_s=rng.choice([1e5, 1e7, 1e9]))
        for S in range(2, min(C + 1, 5) + 1):
            p_dp = solve(g, S, cm)
            p_bi = solve(g, S, cm, method="bisect")
            p_bf = brute_force(g, S, cm)
            tol = 1e-12 + 1e-6 * p_bf.bottleneck_s
            assert abs(p_dp.bottleneck_s - p_bf.bottleneck_s) <= tol, \
                (t, S, p_dp.bottleneck_s, p_bf.bottleneck_s)
            assert abs(p_bi.bottleneck_s - p_bf.bottleneck_s) <= tol, \
                (t, S, p_bi.bottleneck_s, p_bf.bottleneck_s)
            assert len(p_dp.cuts) == S - 1
            assert len(p_dp.codecs) == S - 1
            checked += 1
    assert checked >= 20  # the property actually exercised many cases


def test_solver_beats_or_matches_quantile_on_same_model():
    g = dense_chain([16, 64, 16, 64, 16, 64, 16])
    cm = StageCostModel(g, gen="v4", link_bw_s=1e6)
    for S in (2, 3, 4):
        plan = solve(g, S, cm)
        q = evaluate_cuts(g, auto_cut_points(g, S), cm)
        assert plan.bottleneck_s <= q.bottleneck_s * (1 + 1e-9)


def test_solver_avoids_fat_boundary():
    """The quantile heuristic's worst case: FLOP midpoint on a fat
    activation.  The solver must cut at the thin boundary instead."""
    g = dense_chain([4096, 16, 16], in_width=16)  # fc0 out = 4096 elems
    q = auto_cut_points(g, 2)
    assert q == ["fc0"]  # FLOP-balanced cut lands on the fat boundary
    cm = StageCostModel(g, gen="v4", link_bw_s=1e6)  # slow link
    plan = solve(g, 2, cm)
    assert plan.cuts == ["fc1"]
    assert plan.bottleneck_s < evaluate_cuts(g, q, cm).bottleneck_s
    # and auto_cut_points delegates to the same answer
    assert auto_cut_points(g, 2, objective="bottleneck",
                           cost_model=cm) == ["fc1"]


def test_solver_errors():
    g = dense_chain([8, 8])
    cm = StageCostModel(g, gen="v4")
    with pytest.raises(ValueError, match="valid cut points"):
        solve(g, 50, cm)
    with pytest.raises(ValueError, match="num_stages"):
        solve(g, 0, cm)
    with pytest.raises(ValueError, match="objective"):
        auto_cut_points(g, 2, objective="nope")
    assert solve(g, 1, cm).cuts == []


# -- hybrid replication solver -----------------------------------------------


def test_replicated_dp_matches_brute_force_property():
    """solve_replicated must equal exhaustive (cuts x replica-counts)
    enumeration on random small graphs, for every node budget — and
    never lose to the best cuts-only plan under the same budget."""
    rng = random.Random(11)
    checked = 0
    for t in range(10):
        g = random_graph(rng, t + 100)
        C = len(valid_cut_points(g))
        cm = StageCostModel(
            g, batch=rng.choice([1, 4]), gen="v4",
            link_bw_s=rng.choice([1e5, 1e7, 1e9]))
        for N in (2, 3, 4, 5):
            rp = solve_replicated(g, cm, num_nodes=N)
            bf = brute_force_replicated(g, cm, num_nodes=N)
            tol = 1e-12 + 1e-6 * bf.bottleneck_s
            assert abs(rp.bottleneck_s - bf.bottleneck_s) <= tol, \
                (t, N, rp.bottleneck_s, bf.bottleneck_s, rp.replicas)
            assert rp.num_nodes == sum(rp.replicas) <= N
            assert len(rp.replicas) == rp.num_stages
            assert not any(rp.replicas[k] > 1 and rp.replicas[k + 1] > 1
                           for k in range(rp.num_stages - 1))
            # the hybrid never loses to cuts-only on the same model
            cuts_only = min(
                solve(g, S, cm).bottleneck_s
                for S in range(1, min(N, C + 1) + 1))
            assert rp.bottleneck_s <= cuts_only * (1 + 1e-9), \
                (t, N, rp.bottleneck_s, cuts_only)
            checked += 1
    assert checked >= 30


def test_replication_splits_indivisible_fat_stage():
    """One node 10x heavier than the rest with nowhere left to cut:
    cuts-only plateaus at the fat node's cost, the hybrid halves it by
    replicating the stage that contains it."""
    g = dense_chain([16, 16, 16], in_width=16)
    costs = {n: 1e-5 for n in g.topo_order}
    costs[g.topo_order[1]] = 1e-3  # fc0 output feeds the fat fc1
    free = {"raw": CodecSpec("raw", 1.0, 1e14, 1e14)}
    cm = StageCostModel(g, gen="v4", link_bw_s=1e13, codecs=free,
                        node_costs=costs)
    cuts_only = min(solve(g, S, cm).bottleneck_s for S in (1, 2, 3))
    assert cuts_only >= 1e-3 * (1 - 1e-9)  # the fat node is a floor
    rp = solve_replicated(g, cm, num_nodes=4)
    assert rp.bottleneck_s < cuts_only / 1.9  # >= ~2x from replication
    k = max(range(rp.num_stages), key=lambda i: rp.replicas[i])
    assert rp.replicas[k] > 1
    # JSON carries the replica layout
    d = rp.to_json()
    assert d["replicas"] == rp.replicas and d["num_nodes"] == rp.num_nodes
    assert len(d["stage_effective_ms"]) == rp.num_stages
    json.dumps(d)


def test_replicated_comm_model_fan_parallelism():
    """enc/r_up + wire + dec/r_down: replicating the upstream stage
    parallelizes the hop's encode side only, the downstream its decode
    side only, and the wire term never divides."""
    spec = CodecSpec("x", ratio=2.0, encode_bytes_per_s=1e6,
                     decode_bytes_per_s=2e6)
    g = dense_chain([256, 16], in_width=16)
    # host_sync_bw_s=0: this test checks the pure codec fan algebra
    # (the host-sync halves ride enc/dec and would shift the constants)
    cm = StageCostModel(g, gen="v4", link_bw_s=1e6, codecs={"x": spec},
                        host_sync_bw_s=0)
    raw = cm.cut_bytes("fc0")
    enc, wire, dec = cm.comm_parts("fc0", "x")
    assert enc == pytest.approx(raw / 1e6)
    assert dec == pytest.approx(raw / 2e6)
    assert wire == pytest.approx((raw / 2.0) / 1e6)
    _, s = cm.best_codec_replicated("fc0", 2, 3)
    assert s == pytest.approx(enc / 2 + wire + dec / 3)
    # singleton case must collapse exactly to the old comm model
    _, s1 = cm.best_codec_replicated("fc0", 1, 1)
    assert s1 == pytest.approx(cm.comm_seconds("fc0", "x"))


def test_evaluate_cuts_replicated_and_validation():
    g = dense_chain([16, 16, 16])
    cm = StageCostModel(g, gen="v4")
    p = evaluate_cuts(g, ["fc0"], cm, replicas=[1, 2])
    assert p.replicas == [1, 2] and p.num_nodes == 3
    with pytest.raises(ValueError, match="replica counts"):
        evaluate_cuts(g, ["fc0"], cm, replicas=[1, 2, 1])
    with pytest.raises(ValueError, match="adjacent"):
        evaluate_cuts(g, ["fc0", "fc1"], cm, replicas=[1, 2, 2])


def test_sweep_nodes_recommendation():
    g = dense_chain([16, 16, 16, 16])
    cm = StageCostModel(g, gen="v4", link_bw_s=1e9)
    sw = sweep_nodes(g, cm, max_nodes=4)
    bots = [p.bottleneck_s for p in sw["plans"]]
    assert all(b2 <= b1 * (1 + 1e-9) for b1, b2 in zip(bots, bots[1:]))
    sw2 = sweep_nodes(g, cm, max_nodes=4, latency_target_s=1e6)
    assert sw2["target_met"] is True
    assert sw2["recommended"].num_nodes == 1


def test_replan_replicated_keeps_budget_and_moves_replicas():
    """Telemetry shows a stage 10x slower than modeled: the replicated
    replan must re-solve under the SAME node budget and shift replicas
    toward the measured hotspot."""
    g = dense_chain([64] * 6, in_width=64)
    free = {"raw": CodecSpec("raw", 1.0, 1e15, 1e15)}
    cm = StageCostModel(g, gen="v4", link_bw_s=1e13, codecs=free)
    plan = solve_replicated(g, cm, num_nodes=4)
    order = g.topo_order
    bounds = [0] + [order.index(c) + 1 for c in plan.cuts] + [len(order)]
    snap = {}
    for k in range(plan.num_stages):
        names = order[bounds[k]:bounds[k + 1]]
        factor = 10.0 if k == 0 else 1.0
        snap[f"p.stage{k}.latency_s"] = {
            "count": 8, "p50": cm.compute_seconds(names) * factor}
    rp = replan(g, plan, snap, cm)
    assert rp.corrections[0] == pytest.approx(10.0, rel=1e-6)
    assert rp.new_plan.num_nodes <= plan.num_nodes
    assert rp.new_plan.replicas is not None
    assert rp.predicted_improvement >= 1.0
    json.dumps(rp.to_json())


def test_measured_stage_seconds_averages_replicas():
    stats = [{"stage": 1, "replica": 0,
              "infer_latency_s": {"count": 4, "p50": 0.4}},
             {"stage": 1, "replica": 1,
              "infer_latency_s": {"count": 4, "p50": 0.6}},
             {"stage": 0, "infer_latency_s": {"count": 4, "p50": 0.1}}]
    got = measured_stage_seconds(stats)
    assert got == {0: pytest.approx(0.1), 1: pytest.approx(0.5)}


def test_cli_plan_nodes_json(capsys):
    from defer_tpu.cli import main
    main(["plan", "--model", "resnet_tiny", "--nodes", "4",
          "--link-bw", "1e8", "--json"])
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    p = d["plan"]
    assert sum(p["replicas"]) == p["num_nodes"] <= 4
    assert d["predicted_speedup_vs_cuts_only"] >= 1.0
    assert d["cuts_only"]["bottleneck_ms"] >= p["bottleneck_ms"]


# -- codec selection ---------------------------------------------------------


def _codec_table():
    return {
        "raw": CodecSpec("raw", 1.0, 8e9, 8e9),
        "bf8": CodecSpec("bf8", 4.0, 2e8, 4e8, lossy=True),
    }


def test_per_hop_codec_selection_follows_link_bandwidth():
    g = dense_chain([4096, 16, 16], in_width=16)
    # slow link: shipping 4x fewer bytes beats the encode cost
    slow = StageCostModel(g, gen="v4", link_bw_s=1e6,
                          codecs=_codec_table())
    assert slow.best_codec("fc0")[0] == "bf8"
    # ICI-class link: the wire is nearly free, encode time dominates
    fast = StageCostModel(g, gen="v4", link_bw_s=4.5e10,
                          codecs=_codec_table())
    assert fast.best_codec("fc0")[0] == "raw"
    # lossless_only drops the blockfloat candidates entirely
    lossless = StageCostModel(g, gen="v4", link_bw_s=1e6,
                              codecs=_codec_table(), lossless_only=True)
    assert "bf8" not in lossless.codecs


def test_plan_json_shape():
    g = dense_chain([16, 16, 16])
    plan = solve(g, 2, StageCostModel(g, gen="v4"))
    d = plan.to_json()
    assert d["num_stages"] == 2 and len(d["cuts"]) == 1
    assert len(d["hop_codecs"]) == 1
    assert len(d["stage_compute_ms"]) == 2 and len(d["hop_comm_ms"]) == 1
    assert d["bound_by"] in ("compute", "comm")
    json.dumps(d)  # JSON-serializable end to end


def test_sweep_stages_recommendation():
    g = dense_chain([16, 16, 16, 16, 16, 16])
    cm = StageCostModel(g, gen="v4", link_bw_s=1e9)
    sw = sweep_stages(g, cm, max_stages=4)
    assert [p.num_stages for p in sw["plans"]] == [1, 2, 3, 4]
    # unmeetable target: fall back to the best plan, target_met False
    sw2 = sweep_stages(g, cm, max_stages=4, latency_target_s=1e-30)
    assert sw2["target_met"] is False
    # trivially-met target: recommend the FEWEST stages
    sw3 = sweep_stages(g, cm, max_stages=4, latency_target_s=1e6)
    assert sw3["target_met"] is True
    assert sw3["recommended"].num_stages == 1


# -- quantile greedy regressions ---------------------------------------------


def test_quantile_tail_pool_guard_skewed_costs():
    """Skewed measured costs push every quantile target to the curve's
    tail; the greedy pick must still leave enough candidates for the
    later cuts instead of exhausting the pool (regression for the
    restricted-candidate guard in auto_cut_points)."""
    g = dense_chain([16] * 10)
    order = g.topo_order
    # virtually all measured cost on the last node: every target sits
    # at the tail of the cumulative curve
    costs = {n: 1e-6 for n in order}
    costs[order[-1]] = 1e3
    for S in (3, 4, 5, 6):
        cuts = auto_cut_points(g, S, costs=costs)
        assert len(cuts) == S - 1
        idx = [order.index(c) for c in cuts]
        assert idx == sorted(idx) and len(set(idx)) == len(idx)
    # same shape through the partitioner's new costs= path
    stages = partition(g, num_stages=4, costs=costs)
    assert len(stages) == 4
    with pytest.raises(ValueError, match="nothing to balance"):
        partition(g, ["fc0"], costs=costs)


# -- boundary bytes / sock-buf sizing ----------------------------------------


def test_max_activation_bytes():
    g = dense_chain([4096, 16], in_width=16)
    cuts = ["fc0"]
    elems = max_activation_elems(g, cuts)
    assert elems == 4096
    assert max_activation_bytes(g, cuts) == 4096 * 4  # f32 itemsize
    assert max_activation_bytes(g, cuts, batch=8) == 4096 * 4 * 8
    # thin cut only: the graph input/output still bound the answer
    assert max_activation_bytes(g, ["fc1"]) == 16 * 4


def test_default_sock_buf_clamps():
    from defer_tpu.transport.framed import default_sock_buf
    assert default_sock_buf(100) == 1 << 16            # floor
    assert default_sock_buf(1 << 20) == 2 << 20        # 2x frame
    assert default_sock_buf(1 << 30) == 1 << 23        # ceil


# -- telemetry replan --------------------------------------------------------


def test_measured_stage_seconds_both_sources():
    snap = {
        "pipeline0.stage0.latency_s": {"count": 9, "p50": 0.02,
                                       "mean": 0.05},
        "pipeline0.stage1.latency_s": {"count": 9, "p50": 0.004},
        "pipeline0.stage2.latency_s": {"count": 0},   # empty: skipped
        "pipeline0.push_latency_s": {"count": 9, "p50": 1.0},  # not a stage
        "transport.tx_bytes": 123,
    }
    got = measured_stage_seconds(snap)
    assert got == {0: 0.02, 1: 0.004}
    assert measured_stage_seconds(snap, quantile="mean")[0] == 0.05
    stats = [{"stage": 1, "infer_latency_s": {"count": 4, "p50": 0.5}},
             {"stage": None, "infer_latency_s": {"count": 4, "p50": 9.0}},
             {"stage": 0, "infer_latency_s": {"count": 0}}]
    assert measured_stage_seconds(stats) == {1: 0.5}


def test_replan_moves_cut_toward_measured_hotspot():
    """Telemetry says stage 0 is 10x slower than the model predicted:
    the replan must (a) scale stage-0 node costs up, (b) move the cut
    earlier, (c) predict an improvement over keeping the old cuts."""
    # near-free transport so compute (not the equal-size hops) decides
    # cuts: the hop codec's modeled memcpy would otherwise dominate the
    # nanosecond-scale roofline of a toy dense chain
    g = dense_chain([512] * 8, in_width=512)
    free = {"raw": CodecSpec("raw", 1.0, 1e15, 1e15)}
    cm = StageCostModel(g, gen="v4", link_bw_s=1e13, codecs=free,
                        host_sync_bw_s=0)
    plan = solve(g, 2, cm)
    assert plan.cuts == ["fc3"]  # balanced 4/4 before telemetry lands
    pred0 = cm.compute_seconds(
        g.topo_order[: g.topo_order.index(plan.cuts[0]) + 1])
    snap = {
        "pipeline0.stage0.latency_s": {"count": 20, "p50": pred0 * 10},
        # stage 1 measured exactly as predicted
        "pipeline0.stage1.latency_s": {
            "count": 20,
            "p50": cm.compute_seconds(
                g.topo_order[g.topo_order.index(plan.cuts[0]) + 1:])},
    }
    rp = replan(g, plan, snap, cm)
    assert rp.corrections[0] == pytest.approx(10.0, rel=1e-6)
    assert rp.corrections[1] == pytest.approx(1.0, rel=1e-6)
    assert rp.moved
    order = g.topo_order
    assert order.index(rp.new_plan.cuts[0]) < order.index(plan.cuts[0])
    assert rp.predicted_improvement > 1.0
    d = rp.to_json()
    json.dumps(d)
    assert d["moved"] is True


def test_replan_noop_when_model_is_right():
    g = dense_chain([16] * 6)
    cm = StageCostModel(g, gen="v4", link_bw_s=1e9)
    plan = solve(g, 3, cm)
    order = g.topo_order
    bounds = [0] + [order.index(c) + 1 for c in plan.cuts] + [len(order)]
    snap = {}
    for k in range(3):
        names = order[bounds[k]:bounds[k + 1]]
        snap[f"p.stage{k}.latency_s"] = {
            "count": 5, "p50": cm.compute_seconds(names)}
    rp = replan(g, plan, snap, cm)
    assert all(v == pytest.approx(1.0, rel=1e-6)
               for v in rp.corrections.values())
    assert rp.new_plan.bottleneck_s == pytest.approx(
        plan.bottleneck_s, rel=1e-6)


# -- CLI machine-readable surface --------------------------------------------


def test_cli_partition_json(capsys):
    from defer_tpu.cli import main
    main(["partition", "--model", "resnet_tiny", "--stages", "3",
          "--json"])
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["model"] == "resnet_tiny" and d["num_stages"] == 3
    assert len(d["cuts"]) == 2 and len(d["stages"]) == 3
    assert d["max_activation_bytes"] > 0
    assert "buffer" in d and "plan" not in d
    for s in d["stages"]:
        assert {"index", "nodes", "in_shape", "out_shape",
                "boundary_bytes"} <= set(s)


def test_cli_partition_json_bottleneck(capsys):
    from defer_tpu.cli import main
    main(["partition", "--model", "resnet_tiny", "--stages", "3",
          "--balance", "bottleneck", "--link-bw", "1e8", "--json"])
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["cuts"] == d["plan"]["cuts"]
    assert len(d["plan"]["hop_codecs"]) == 2
    assert d["plan"]["bottleneck_ms"] > 0


def test_cli_plan_json(capsys, tmp_path):
    from defer_tpu.cli import main
    main(["plan", "--model", "resnet_tiny", "--stages", "3",
          "--link-bw", "1e8", "--json"])
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["plan"]["num_stages"] == 3
    assert d["quantile"]["objective"] == "quantile"
    assert d["predicted_speedup_vs_quantile"] >= 1.0
    # replan flow: feed a fabricated --metrics-out style snapshot back in
    snap = {"registry": {
        "pipeline0.stage0.latency_s": {"count": 10, "p50": 0.5},
        "pipeline0.stage1.latency_s": {"count": 10, "p50": 0.001},
        "pipeline0.stage2.latency_s": {"count": 10, "p50": 0.001},
    }}
    f = tmp_path / "metrics.json"
    f.write_text(json.dumps(snap))
    main(["plan", "--model", "resnet_tiny", "--stages", "3",
          "--link-bw", "1e8", "--replan", str(f), "--json"])
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["replan"]["corrections"]["0"] > 1.0
    assert d["replan"]["new"]["num_stages"] == 3


def test_cli_plan_sweep_json(capsys):
    from defer_tpu.cli import main
    main(["plan", "--model", "resnet_tiny", "--sweep", "3", "--json"])
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert [p["num_stages"] for p in d["sweep"]] == [1, 2, 3]
    assert d["recommended"]["num_stages"] in (1, 2, 3)
