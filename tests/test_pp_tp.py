"""Composed pipeline x tensor parallelism: an SPMD pipeline whose stages
are themselves Megatron-sharded over the "model" mesh axis must reproduce
the single-device forward — the 3D (data x stage x model) extension of the
partition-equivalence invariant.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu import (Defer, DeferConfig, SpmdPipeline, partition,
                       pipeline_mesh)
from defer_tpu.models import bert_tiny


@pytest.fixture(scope="module")
def bert():
    graph = bert_tiny()
    params = graph.init(jax.random.key(0))
    ids = (np.arange(3 * 2 * 16).reshape(3, 2, 16) % 100).astype(np.int32)
    ref = np.stack([np.asarray(graph.apply(params, jnp.asarray(b)))
                    for b in ids])
    return graph, params, ids, ref


def test_pp_tp_matches_full(bert):
    graph, params, ids, ref = bert
    stages = partition(graph, num_stages=2)
    mesh = pipeline_mesh(2, tensor_parallel=2)
    assert mesh.shape == {"data": 1, "stage": 2, "model": 2}
    pipe = SpmdPipeline(stages, params, mesh=mesh, microbatch=2, chunk=3)
    out = pipe.run(ids.astype(np.float32))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_pp_tp_dp_matches_full(bert):
    graph, params, ids, ref = bert
    stages = partition(graph, num_stages=2)
    mesh = pipeline_mesh(2, data_parallel=2, tensor_parallel=2)
    assert mesh.devices.size == 8
    pipe = SpmdPipeline(stages, params, mesh=mesh, microbatch=2, chunk=3)
    out = pipe.run(ids.astype(np.float32))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_defer_api_tensor_parallel(bert):
    graph, params, ids, ref = bert
    defer = Defer(config=DeferConfig(microbatch=2, chunk=3,
                                     tensor_parallel=2))
    out = defer.run(graph, params, ids.astype(np.float32), num_stages=4)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_tp_weight_buffer_is_sharded(bert):
    """Under TP the flat weight buffer carries per-rank shards: its Pmax is
    about half the tp=1 Pmax for bert_tiny (qkv/fc dominate)."""
    graph, params, _, _ = bert
    stages = partition(graph, num_stages=2)
    p1 = SpmdPipeline(stages, params, mesh=pipeline_mesh(2), microbatch=2)
    p2 = SpmdPipeline(stages, params, mesh=pipeline_mesh(
        2, tensor_parallel=2), microbatch=2)
    assert p2._w.shape[0] == 2 and p2._w.ndim == 3
    assert p2._w.shape[-1] < p1._w.shape[-1]
