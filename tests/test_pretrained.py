"""Pretrained-weight import: torchvision-layout checkpoints -> graph params.

Capability parity with the reference's trained-model benchmark
(``ResNet50(weights="imagenet")``, reference test/test.py:13-14): a user
holding a standard ResNet50 checkpoint can deploy it on the pipeline.
"""

import numpy as np
import pytest

import jax

from defer_tpu.models.resnet import resnet, resnet50
from defer_tpu.utils.checkpoint import save_params
from defer_tpu.utils.pretrained import (convert_resnet50_state_dict,
                                        load_pretrained_resnet50,
                                        resnet50_torch_mapping)

DEPTHS = (1, 1)  # two bottleneck blocks: projection + identity paths


@pytest.fixture(scope="module")
def small():
    g = resnet(list(DEPTHS), width=8, num_classes=10, image_size=32,
               name="resnet_small")
    return g, g.init(jax.random.key(0))


def _random_sd_from_mapping(mapping, expected, rng):
    """Random torchvision-layout state dict whose inverse-transformed
    shapes match ``expected`` — the shared fixture generator for every
    family's logit-match test (torch-native shapes per transform kind)."""
    sd = {}
    for (_node, _leaf), (src, tf) in mapping.items():
        if src in sd:
            continue
        want = np.shape(expected[_node][_leaf])
        if tf.__name__ == "_conv_t":
            shp = (want[3], want[2], want[0], want[1])
        elif tf.__name__ in ("_fc_t", "_fc1_t"):
            shp = (want[1], want[0])
        else:
            shp = want
        val = rng.standard_normal(shp) * 0.1
        if src.endswith("running_var"):
            val = np.abs(val) + 0.5
        sd[src] = val.astype(np.float32)
    return sd


def _synthetic_torch_sd(expected, depths):
    """Random state_dict in torchvision layout whose inverse-transformed
    values equal a reference pytree (so conversion is exactly checkable)."""
    rng = np.random.default_rng(0)
    mapping = resnet50_torch_mapping(depths)
    sd, truth = {}, {}
    inv = {(2, 3, 1, 0): (3, 2, 0, 1)}  # HWIO->OIHW inverse
    for (node, leaf), (src, tf) in mapping.items():
        want = np.shape(expected[node][leaf])
        val = rng.standard_normal(want).astype(np.float32)
        truth[(node, leaf)] = val
        if tf.__name__ == "_conv_t":
            sd[src] = np.transpose(val, inv[(2, 3, 1, 0)])
        elif tf.__name__ == "_fc_t":
            sd[src] = np.transpose(val, (1, 0))
        else:
            sd[src] = val
    return sd, truth


def test_convert_small_resnet_exact(small):
    g, expected = small
    sd, truth = _synthetic_torch_sd(expected, DEPTHS)
    params = convert_resnet50_state_dict(sd, expected, DEPTHS)
    # structure identical to graph.init's
    assert (jax.tree.structure(params) == jax.tree.structure(expected))
    for (node, leaf), val in truth.items():
        np.testing.assert_array_equal(params[node][leaf], val)
    # the converted params run
    y = jax.jit(g.apply)(params, np.zeros((1, 32, 32, 3), np.float32))
    assert y.shape == (1, 10)


def test_npz_and_flat_roundtrip(tmp_path, small):
    g, expected = small
    sd, truth = _synthetic_torch_sd(expected, DEPTHS)
    # torchvision-layout npz
    p = tmp_path / "ckpt.npz"
    np.savez(p, **sd)
    params = load_pretrained_resnet50(str(p), g, DEPTHS)
    np.testing.assert_array_equal(params["conv2d"]["w"],
                                  truth[("conv2d", "w")])
    # our own flat node/leaf layout (utils/checkpoint save format)
    p2 = tmp_path / "own.npz"
    save_params(str(p2), params)
    again = load_pretrained_resnet50(str(p2), g, DEPTHS)
    np.testing.assert_array_equal(again["predictions"]["b"],
                                  truth[("predictions", "b")])


def test_resnet_convert_and_logit_match():
    """ResNet conversion must reproduce a torch functional reference:
    validates v1.5 stride placement (3x3 carries the stride, like
    torchvision), symmetric stride-2 padding, and BN running stats."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    from defer_tpu.models.resnet import resnet
    from defer_tpu.utils.pretrained import resnet50_torch_mapping

    depths = (1, 1)
    g = resnet(list(depths), width=8, num_classes=10, image_size=32,
               name="resnet_fixture")
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = resnet50_torch_mapping(depths)

    rng = np.random.default_rng(8)
    sd = _random_sd_from_mapping(mapping, expected, rng)

    params = convert_resnet50_state_dict(sd, expected, depths)

    def tt(k):
        return torch.from_numpy(sd[k]).double()

    def conv_bn(t, conv, bn, stride, relu=True):
        w = tt(f"{conv}.weight")
        t = F.conv2d(t, w, None, stride=stride,
                     padding=(w.shape[-1] - 1) // 2)
        t = F.batch_norm(t, tt(f"{bn}.running_mean"),
                         tt(f"{bn}.running_var"), tt(f"{bn}.weight"),
                         tt(f"{bn}.bias"), training=False, eps=1e-5)
        return F.relu(t) if relu else t

    x = rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(x, (0, 3, 1, 2))).double()
    t = conv_bn(t, "conv1", "bn1", 2)
    t = F.max_pool2d(t, 3, 2, padding=1)
    for s, blocks in enumerate(depths):
        for i in range(blocks):
            stride = 2 if (s > 0 and i == 0) else 1
            base = f"layer{s + 1}.{i}"
            short = t
            if i == 0:
                short = conv_bn(t, f"{base}.downsample.0",
                                f"{base}.downsample.1", stride, relu=False)
            t2 = conv_bn(t, f"{base}.conv1", f"{base}.bn1", 1)
            t2 = conv_bn(t2, f"{base}.conv2", f"{base}.bn2", stride)
            t2 = conv_bn(t2, f"{base}.conv3", f"{base}.bn3", 1, relu=False)
            t = F.relu(t2 + short)
    t = t.mean(dim=(2, 3))
    t = F.linear(t, tt("fc.weight"), tt("fc.bias"))
    ref = t.numpy()

    ours = np.asarray(jax.jit(g.apply)(params, x), np.float64)
    np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-3)


def _torch_vgg_logits(sd, cfg, x_nhwc):
    """Independent NCHW reference forward of a torchvision-layout VGG
    state_dict — validates layout transforms end to end (esp. the
    CHW-vs-HWC flatten permutation on fc1)."""
    import torch
    import torch.nn.functional as F

    t = torch.from_numpy(np.transpose(x_nhwc, (0, 3, 1, 2))).double()
    i = 0
    for v in cfg:
        if v == "M":
            t = F.max_pool2d(t, 2, 2)
            i += 1
        else:
            t = F.relu(F.conv2d(
                t, torch.from_numpy(sd[f"features.{i}.weight"]).double(),
                torch.from_numpy(sd[f"features.{i}.bias"]).double(),
                padding=1))
            i += 2
    t = torch.flatten(t, 1)
    for j, act in ((0, True), (3, True), (6, False)):
        t = F.linear(t,
                     torch.from_numpy(sd[f"classifier.{j}.weight"]).double(),
                     torch.from_numpy(sd[f"classifier.{j}.bias"]).double())
        if act:
            t = F.relu(t)
    return t.numpy()


def test_vgg_convert_and_logit_match():
    """VGG19-layout conversion: converted params must reproduce the torch
    reference forward's logits (catches flatten-order and padding bugs
    that shape checks cannot)."""
    torch = pytest.importorskip("torch")  # noqa: F841
    from defer_tpu.models.vgg import vgg
    from defer_tpu.utils.pretrained import (convert_state_dict,
                                            vgg_torch_mapping)

    cfg = [8, "M", 16, "M"]  # two blocks, 8x8x16 pre-flatten at 32px
    g = vgg(cfg, num_classes=10, image_size=32, fc_width=32,
            name="vgg_fixture")
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))

    rng = np.random.default_rng(3)
    mapping = vgg_torch_mapping(cfg, (8, 8, 16))
    sd = _random_sd_from_mapping(mapping, expected, rng)

    params = convert_state_dict(mapping, sd, expected, "VGG-fixture")
    x = rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
    ours = np.asarray(jax.jit(g.apply)(params, x), np.float64)
    ref = _torch_vgg_logits(sd, cfg, x)
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_mobilenet_v2_convert_and_logit_match():
    """MobileNetV2 conversion at full architecture (width 0.25 for speed):
    converted params must reproduce a torch functional reference —
    validates the builder-order mapping, depthwise layout, stride-2
    symmetric padding, and BN folding of running stats."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    from defer_tpu.models.mobilenet import _V2_CFG, mobilenet_v2
    from defer_tpu.utils.pretrained import (convert_state_dict,
                                            mobilenet_v2_torch_mapping)

    g = mobilenet_v2(num_classes=10, image_size=32, width_mult=0.25,
                     name="mnv2_fixture")
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = mobilenet_v2_torch_mapping()

    rng = np.random.default_rng(5)
    sd = _random_sd_from_mapping(mapping, expected, rng)

    params = convert_state_dict(mapping, sd, expected, "MNV2-fixture")

    def tt(key):
        return torch.from_numpy(sd[key]).double()

    def cbr(t, conv, bn, stride, groups=1, relu=True):
        w = tt(f"{conv}.weight")
        t = F.conv2d(t, w, None, stride=stride,
                     padding=(w.shape[-1] - 1) // 2, groups=groups)
        t = F.batch_norm(t, tt(f"{bn}.running_mean"),
                         tt(f"{bn}.running_var"), tt(f"{bn}.weight"),
                         tt(f"{bn}.bias"), training=False, eps=1e-5)
        return F.relu6(t) if relu else t

    x = rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(x, (0, 3, 1, 2))).double()
    t = cbr(t, "features.0.0", "features.0.1", 2)
    f = 1
    for expand, _out, reps, stride in _V2_CFG:
        for i in range(reps):
            s = stride if i == 0 else 1
            base = f"features.{f}.conv"
            f += 1
            inp = t
            if expand != 1:
                t = cbr(t, f"{base}.0.0", f"{base}.0.1", 1)
                t = cbr(t, f"{base}.1.0", f"{base}.1.1", s,
                        groups=t.shape[1])
                t = cbr(t, f"{base}.2", f"{base}.3", 1, relu=False)
            else:
                t = cbr(t, f"{base}.0.0", f"{base}.0.1", s,
                        groups=t.shape[1])
                t = cbr(t, f"{base}.1", f"{base}.2", 1, relu=False)
            if s == 1 and inp.shape[1] == t.shape[1]:
                t = t + inp
    t = cbr(t, f"features.{f}.0", f"features.{f}.1", 1)
    t = t.mean(dim=(2, 3))
    t = F.linear(t, tt("classifier.1.weight"), tt("classifier.1.bias"))
    ref = t.numpy()

    ours = np.asarray(jax.jit(g.apply)(params, x), np.float64)
    np.testing.assert_allclose(ours, ref, rtol=1e-3, atol=1e-3)


def test_bert_convert_and_logit_match():
    """HF-layout BERT conversion (fused qkv, folded segment embedding,
    post-LN residuals, exact GELU, eps 1e-12): converted params must
    reproduce a torch functional reference of HF's forward."""
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    from defer_tpu.models.bert import bert
    from defer_tpu.utils.pretrained import (bert_torch_mapping,
                                            convert_state_dict)

    L, D, H, T, V = 2, 32, 2, 16, 50
    g = bert(L, D, H, T, vocab=V, name="bert_fixture")
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = bert_torch_mapping(L, max_len=T)

    rng = np.random.default_rng(17)
    sd = {}
    e = "embeddings"
    sd[f"{e}.word_embeddings.weight"] = \
        (rng.standard_normal((V, D)) * 0.1).astype(np.float32)
    # the REAL checkpoint carries a longer (512-row) positional table
    # than the deployed seq_len — the importer must crop it
    sd[f"{e}.position_embeddings.weight"] = \
        (rng.standard_normal((4 * T, D)) * 0.1).astype(np.float32)
    sd[f"{e}.token_type_embeddings.weight"] = \
        (rng.standard_normal((2, D)) * 0.1).astype(np.float32)
    sd[f"{e}.LayerNorm.weight"] = np.ones(D, np.float32)
    sd[f"{e}.LayerNorm.bias"] = np.zeros(D, np.float32)
    for i in range(L):
        b = f"encoder.layer.{i}"
        for part, shape in (
                (f"{b}.attention.self.query", (D, D)),
                (f"{b}.attention.self.key", (D, D)),
                (f"{b}.attention.self.value", (D, D)),
                (f"{b}.attention.output.dense", (D, D)),
                (f"{b}.intermediate.dense", (4 * D, D)),
                (f"{b}.output.dense", (D, 4 * D))):
            sd[f"{part}.weight"] = \
                (rng.standard_normal(shape) * 0.1).astype(np.float32)
            sd[f"{part}.bias"] = \
                (rng.standard_normal(shape[0]) * 0.02).astype(np.float32)
        for ln in (f"{b}.attention.output.LayerNorm", f"{b}.output.LayerNorm"):
            sd[f"{ln}.weight"] = \
                (1 + rng.standard_normal(D) * 0.02).astype(np.float32)
            sd[f"{ln}.bias"] = \
                (rng.standard_normal(D) * 0.02).astype(np.float32)
    sd["pooler.dense.weight"] = \
        (rng.standard_normal((D, D)) * 0.1).astype(np.float32)
    sd["pooler.dense.bias"] = \
        (rng.standard_normal(D) * 0.02).astype(np.float32)

    params = convert_state_dict(mapping, sd, expected, "BERT-fixture")

    def tt(k):
        return torch.from_numpy(sd[k]).double()

    def layer_norm(x, w, b):
        return F.layer_norm(x, (D,), w, b, eps=1e-12)

    ids = rng.integers(0, V, (2, T))
    x = (tt(f"{e}.word_embeddings.weight")[torch.from_numpy(ids)]
         + tt(f"{e}.position_embeddings.weight")[:T][None]
         + tt(f"{e}.token_type_embeddings.weight")[0][None, None])
    x = layer_norm(x, tt(f"{e}.LayerNorm.weight"), tt(f"{e}.LayerNorm.bias"))
    hd = D // H
    for i in range(L):
        b = f"encoder.layer.{i}"
        a = f"{b}.attention"

        def proj(t_in, part):
            return F.linear(t_in, tt(f"{part}.weight"), tt(f"{part}.bias"))

        q = proj(x, f"{a}.self.query").view(2, T, H, hd).transpose(1, 2)
        k = proj(x, f"{a}.self.key").view(2, T, H, hd).transpose(1, 2)
        v = proj(x, f"{a}.self.value").view(2, T, H, hd).transpose(1, 2)
        att = torch.softmax(q @ k.transpose(-1, -2) / np.sqrt(hd), dim=-1)
        y = (att @ v).transpose(1, 2).reshape(2, T, D)
        y = proj(y, f"{a}.output.dense")
        x = layer_norm(x + y, tt(f"{a}.output.LayerNorm.weight"),
                       tt(f"{a}.output.LayerNorm.bias"))
        y = F.gelu(proj(x, f"{b}.intermediate.dense"))  # exact erf gelu
        y = proj(y, f"{b}.output.dense")
        x = layer_norm(x + y, tt(f"{b}.output.LayerNorm.weight"),
                       tt(f"{b}.output.LayerNorm.bias"))
    ref = torch.tanh(F.linear(x[:, 0], tt("pooler.dense.weight"),
                              tt("pooler.dense.bias"))).numpy()

    ours = np.asarray(jax.jit(g.apply)(params, ids.astype(np.int32)),
                      np.float64)
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_torch_pt_container(tmp_path, small):
    torch = pytest.importorskip("torch")
    g, expected = small
    sd, truth = _synthetic_torch_sd(expected, DEPTHS)
    p = tmp_path / "ckpt.pt"
    torch.save({k: torch.from_numpy(v) for k, v in sd.items()}, str(p))
    params = load_pretrained_resnet50(str(p), g, DEPTHS)
    np.testing.assert_array_equal(params["predictions"]["w"],
                                  truth[("predictions", "w")])


def test_missing_and_mismatched_fail_loudly(small):
    g, expected = small
    sd, _ = _synthetic_torch_sd(expected, DEPTHS)
    bad = dict(sd)
    del bad["conv1.weight"]
    with pytest.raises(ValueError, match="missing"):
        convert_resnet50_state_dict(bad, expected, DEPTHS)
    bad = dict(sd)
    bad["fc.weight"] = np.zeros((7, 7), np.float32)
    with pytest.raises(ValueError, match="mismatch"):
        convert_resnet50_state_dict(bad, expected, DEPTHS)


def test_full_resnet50_mapping_covers_every_leaf():
    """Shape contract against the real flagship model: the torchvision
    mapping addresses exactly the parametric leaves of resnet50()."""
    g = resnet50()
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = resnet50_torch_mapping()
    addressed = set(mapping)
    parametric = {(node, leaf) for node, sub in expected.items()
                  for leaf in sub}
    assert addressed == parametric
    # standard torchvision key census: 53 convs + 53 bns * 4 + fc * 2
    assert len({src for src, _ in mapping.values()}) == 53 + 53 * 4 + 2


# ---------------------------------------------------------------------------
# InceptionV3


def _torch_inception_logits(sd, x_nhwc):
    """Independent NCHW reference forward of a torchvision-layout
    InceptionV3 state_dict (eval semantics: no aux head, no dropout) —
    op-by-op from the torchvision module tree, in float64."""
    import torch
    import torch.nn.functional as F

    def tt(key):
        return torch.from_numpy(sd[key]).double()

    def bconv(t, prefix, stride=1, same=True):
        w = tt(f"{prefix}.conv.weight")
        pad = (w.shape[-2] // 2, w.shape[-1] // 2) if same else 0
        t = F.conv2d(t, w, None, stride=stride, padding=pad)
        t = F.batch_norm(t, tt(f"{prefix}.bn.running_mean"),
                         tt(f"{prefix}.bn.running_var"),
                         tt(f"{prefix}.bn.weight"), tt(f"{prefix}.bn.bias"),
                         training=False, eps=1e-3)
        return F.relu(t)

    def block_a(t, p):
        b1 = bconv(t, f"{p}.branch1x1")
        b5 = bconv(bconv(t, f"{p}.branch5x5_1"), f"{p}.branch5x5_2")
        bd = bconv(bconv(bconv(t, f"{p}.branch3x3dbl_1"),
                         f"{p}.branch3x3dbl_2"), f"{p}.branch3x3dbl_3")
        bp = bconv(F.avg_pool2d(t, 3, 1, 1), f"{p}.branch_pool")
        return torch.cat([b1, b5, bd, bp], 1)

    def block_b(t, p):
        b3 = bconv(t, f"{p}.branch3x3", stride=2, same=False)
        bd = bconv(bconv(bconv(t, f"{p}.branch3x3dbl_1"),
                         f"{p}.branch3x3dbl_2"),
                   f"{p}.branch3x3dbl_3", stride=2, same=False)
        return torch.cat([b3, bd, F.max_pool2d(t, 3, 2)], 1)

    def block_c(t, p):
        b1 = bconv(t, f"{p}.branch1x1")
        b7 = bconv(bconv(bconv(t, f"{p}.branch7x7_1"), f"{p}.branch7x7_2"),
                   f"{p}.branch7x7_3")
        bd = t
        for i in range(1, 6):
            bd = bconv(bd, f"{p}.branch7x7dbl_{i}")
        bp = bconv(F.avg_pool2d(t, 3, 1, 1), f"{p}.branch_pool")
        return torch.cat([b1, b7, bd, bp], 1)

    def block_d(t, p):
        b3 = bconv(bconv(t, f"{p}.branch3x3_1"), f"{p}.branch3x3_2",
                   stride=2, same=False)
        b7 = bconv(bconv(bconv(t, f"{p}.branch7x7x3_1"),
                         f"{p}.branch7x7x3_2"), f"{p}.branch7x7x3_3")
        b7 = bconv(b7, f"{p}.branch7x7x3_4", stride=2, same=False)
        return torch.cat([b3, b7, F.max_pool2d(t, 3, 2)], 1)

    def block_e(t, p):
        b1 = bconv(t, f"{p}.branch1x1")
        m3 = bconv(t, f"{p}.branch3x3_1")
        b3 = torch.cat([bconv(m3, f"{p}.branch3x3_2a"),
                        bconv(m3, f"{p}.branch3x3_2b")], 1)
        md = bconv(bconv(t, f"{p}.branch3x3dbl_1"), f"{p}.branch3x3dbl_2")
        bd = torch.cat([bconv(md, f"{p}.branch3x3dbl_3a"),
                        bconv(md, f"{p}.branch3x3dbl_3b")], 1)
        bp = bconv(F.avg_pool2d(t, 3, 1, 1), f"{p}.branch_pool")
        return torch.cat([b1, b3, bd, bp], 1)

    t = torch.from_numpy(np.transpose(x_nhwc, (0, 3, 1, 2))).double()
    t = bconv(t, "Conv2d_1a_3x3", stride=2, same=False)
    t = bconv(t, "Conv2d_2a_3x3", same=False)
    t = bconv(t, "Conv2d_2b_3x3")
    t = F.max_pool2d(t, 3, 2)
    t = bconv(t, "Conv2d_3b_1x1", same=False)
    t = bconv(t, "Conv2d_4a_3x3", same=False)
    t = F.max_pool2d(t, 3, 2)
    for p in ("Mixed_5b", "Mixed_5c", "Mixed_5d"):
        t = block_a(t, p)
    t = block_b(t, "Mixed_6a")
    for p in ("Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e"):
        t = block_c(t, p)
    t = block_d(t, "Mixed_7a")
    for p in ("Mixed_7b", "Mixed_7c"):
        t = block_e(t, p)
    t = t.mean(dim=(2, 3))
    t = F.linear(t, tt("fc.weight"), tt("fc.bias"))
    return t.numpy()


def test_inception_v3_mapping_covers_every_leaf():
    """The torchvision mapping addresses exactly the parametric leaves of
    inception_v3(): 94 BasicConv2d (conv + 4 BN leaves each) + fc."""
    from defer_tpu.models import inception_v3
    from defer_tpu.utils.pretrained import inception_v3_torch_mapping

    g = inception_v3()
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = inception_v3_torch_mapping()
    addressed = set(mapping)
    parametric = {(node, leaf) for node, sub in expected.items()
                  for leaf in sub}
    assert addressed == parametric
    assert len({src for src, _ in mapping.values()}) == 94 + 94 * 4 + 2


@pytest.mark.slow
def test_inception_v3_convert_and_logit_match():
    """Full-architecture InceptionV3 conversion at 75 px: converted params
    must reproduce the torch reference forward — validates builder order
    vs the torchvision module tree, BN eps=1e-3, VALID/SAME placement,
    and count_include_pad pool-branch semantics."""
    torch = pytest.importorskip("torch")  # noqa: F841
    from defer_tpu.models import inception_v3
    from defer_tpu.utils.pretrained import (convert_state_dict,
                                            inception_v3_torch_mapping)

    g = inception_v3(num_classes=10, image_size=75)
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = inception_v3_torch_mapping()
    rng = np.random.default_rng(11)
    sd = _random_sd_from_mapping(mapping, expected, rng)
    # an ignored aux head must not break conversion
    sd["AuxLogits.conv0.conv.weight"] = np.zeros((128, 768, 1, 1),
                                                 np.float32)
    params = convert_state_dict(mapping, sd, expected, "IV3-fixture")

    x = rng.standard_normal((1, 75, 75, 3)).astype(np.float32)
    ours = np.asarray(jax.jit(g.apply)(params, x), np.float64)
    ref = _torch_inception_logits(sd, x)
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# GPT-2 (HF layout)


def _torch_gpt2_logits(sd, ids):
    """Independent reference forward of an HF-layout GPT-2 state_dict
    (pre-LN, tanh GELU, eps 1e-5, causal mask, tied LM head), float64."""
    import torch
    import torch.nn.functional as F

    def tt(k):
        return torch.from_numpy(sd[k]).double()

    t_ids = torch.from_numpy(ids)
    x = tt("wte.weight")[t_ids] + tt("wpe.weight")[: ids.shape[1]][None]
    n_layers = len({k.split(".")[1] for k in sd if k.startswith("h.")})
    for i in range(n_layers):
        h = f"h.{i}"
        y = F.layer_norm(x, x.shape[-1:], tt(f"{h}.ln_1.weight"),
                         tt(f"{h}.ln_1.bias"), eps=1e-5)
        qkv = y @ tt(f"{h}.attn.c_attn.weight") + tt(f"{h}.attn.c_attn.bias")
        q, k, v = qkv.chunk(3, dim=-1)
        b, t, d = q.shape
        nh = sd["_num_heads"]
        hd = d // nh

        def heads(a):
            return a.reshape(b, t, nh, hd).transpose(1, 2)

        att = heads(q) @ heads(k).transpose(-1, -2) / hd ** 0.5
        mask = torch.tril(torch.ones(t, t, dtype=torch.bool))
        att = att.masked_fill(~mask, float("-inf")).softmax(-1)
        y = (att @ heads(v)).transpose(1, 2).reshape(b, t, d)
        y = y @ tt(f"{h}.attn.c_proj.weight") + tt(f"{h}.attn.c_proj.bias")
        x = x + y
        y = F.layer_norm(x, x.shape[-1:], tt(f"{h}.ln_2.weight"),
                         tt(f"{h}.ln_2.bias"), eps=1e-5)
        y = F.gelu(y @ tt(f"{h}.mlp.c_fc.weight")
                   + tt(f"{h}.mlp.c_fc.bias"), approximate="tanh")
        y = y @ tt(f"{h}.mlp.c_proj.weight") + tt(f"{h}.mlp.c_proj.bias")
        x = x + y
    x = F.layer_norm(x, x.shape[-1:], tt("ln_f.weight"), tt("ln_f.bias"),
                     eps=1e-5)
    return (x @ tt("wte.weight").T).numpy()


def test_gpt2_convert_and_logit_match():
    """HF GPT-2 conversion (Conv1D [in,out] weights, fused c_attn order,
    tied LM head, wpe crop, eps 1e-5) must reproduce the torch reference
    forward — including through the 'transformer.'-prefixed LMHead
    layout and the xla attention path."""
    torch = pytest.importorskip("torch")  # noqa: F841
    from defer_tpu.models.gpt import gpt
    from defer_tpu.utils.pretrained import (convert_state_dict,
                                            gpt2_torch_mapping)

    layers, d, heads, t_model, vocab = 2, 32, 2, 12, 64
    g = gpt(layers, d, heads, t_model, vocab=vocab, ln_eps=1e-5,
            name="gpt2_fixture")
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))

    rng = np.random.default_rng(17)
    hf_len = 24  # HF ships a longer positional table; the import crops
    sd = {
        "wte.weight": rng.standard_normal((vocab, d)).astype(np.float32)
        * 0.1,
        "wpe.weight": rng.standard_normal((hf_len, d)).astype(np.float32)
        * 0.1,
        "ln_f.weight": rng.standard_normal(d).astype(np.float32) * 0.1 + 1,
        "ln_f.bias": rng.standard_normal(d).astype(np.float32) * 0.1,
    }
    for i in range(layers):
        h = f"h.{i}"
        for nm, shp in ((f"{h}.ln_1.weight", (d,)), (f"{h}.ln_1.bias", (d,)),
                        (f"{h}.ln_2.weight", (d,)), (f"{h}.ln_2.bias", (d,)),
                        (f"{h}.attn.c_attn.weight", (d, 3 * d)),
                        (f"{h}.attn.c_attn.bias", (3 * d,)),
                        (f"{h}.attn.c_proj.weight", (d, d)),
                        (f"{h}.attn.c_proj.bias", (d,)),
                        (f"{h}.mlp.c_fc.weight", (d, 4 * d)),
                        (f"{h}.mlp.c_fc.bias", (4 * d,)),
                        (f"{h}.mlp.c_proj.weight", (4 * d, d)),
                        (f"{h}.mlp.c_proj.bias", (d,))):
            v = rng.standard_normal(shp).astype(np.float32) * 0.1
            if nm.endswith("ln_1.weight") or nm.endswith("ln_2.weight"):
                v = v + 1
            sd[nm] = v

    mapping = gpt2_torch_mapping(layers, t_model)
    params = convert_state_dict(mapping, sd, expected, "GPT2-fixture")
    # the cropped positional table must equal the HF table's prefix
    np.testing.assert_array_equal(params["embeddings"]["wpe"],
                                  sd["wpe.weight"][:t_model])
    # tied head: w == wte.T, zero bias
    np.testing.assert_array_equal(params["lm_head"]["w"],
                                  sd["wte.weight"].T)
    assert not params["lm_head"]["b"].any()

    ids = rng.integers(0, vocab, (1, t_model)).astype(np.int64)
    ours = np.asarray(jax.jit(g.apply)(params, ids.astype(np.int32)),
                      np.float64)[0]
    sd["_num_heads"] = heads
    ref = _torch_gpt2_logits(sd, ids)[0]
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gpt2_load_pretrained_front_door(tmp_path):
    """load_pretrained('gpt2', ...) accepts the transformer.-prefixed
    LMHead state dict written as npz and round-trips through the graph."""
    from defer_tpu.models.gpt import gpt
    from defer_tpu.utils.pretrained import load_pretrained

    layers, d, heads, t_model, vocab = 2, 32, 2, 12, 64
    g = gpt(layers, d, heads, t_model, vocab=vocab, ln_eps=1e-5,
            name="gpt2_fixture")
    rng = np.random.default_rng(23)
    from defer_tpu.utils.pretrained import gpt2_torch_mapping
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    sd = {}
    for (_n, _l), (src, tf) in gpt2_torch_mapping(layers, 24).items():
        if src in sd:
            continue
        sd[src] = rng.standard_normal(
            (vocab, d) if src == "wte.weight"
            else (24, d) if src == "wpe.weight"
            else np.shape(jax.tree.leaves(expected[_n])[0])
        ).astype(np.float32)
    # exact shapes for the block leaves instead of the guess above
    for (_n, _l), (src, tf) in gpt2_torch_mapping(layers, t_model).items():
        if tf.__name__ == "_ident" and _n.startswith("block_"):
            path = _l.split("/")
            want = expected[_n]
            for part in path:
                want = want[part]
            sd[src] = rng.standard_normal(np.shape(want)).astype(np.float32)
    p = tmp_path / "gpt2.npz"
    np.savez(p, **{f"transformer.{k}": v for k, v in sd.items()})
    params = load_pretrained("gpt2", str(p), g)
    y = jax.jit(g.apply)(params, np.zeros((1, t_model), np.int32))
    assert y.shape == (1, t_model, vocab)


def test_gpt2_decode_path_matches_apply_at_hf_eps():
    """Token-by-token decode must agree with full-sequence apply on an
    ln_eps=1e-5 graph — pins the decode path's eps threading (a
    regression to the default 1e-6 silently diverges imported GPT-2
    generation from its own prefill forward)."""
    from defer_tpu import Defer, DeferConfig
    from defer_tpu.models.gpt import gpt

    g = gpt(2, 32, 2, 24, vocab=64, ln_eps=1e-5, name="gpt2_eps_fixture")
    params = g.init(jax.random.key(2))
    # shrink the embedding scale so pre-LN variances sit near eps — a
    # 1e-6-vs-1e-5 epsilon mismatch then visibly flips greedy tokens
    # (verified by mutation: reverting the decode-path eps threading
    # fails this test)
    emb = params["embeddings"]
    params = dict(params, embeddings={
        "wte": emb["wte"] * 1e-3, "wpe": emb["wpe"] * 1e-3})
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, 64, (2, 4)).astype(np.int64)

    fwd = jax.jit(g.apply)
    out = np.array(prompt)
    for _ in range(8):
        logits = np.asarray(fwd(params, out.astype(np.int32)))
        nxt = logits[:, out.shape[1] - 1].argmax(-1)
        out = np.concatenate([out, nxt[:, None]], axis=1)

    defer = Defer(config=DeferConfig(microbatch=2, chunk=4))
    toks = defer.generate(g, params, prompt, 8, num_stages=2)
    np.testing.assert_array_equal(np.asarray(toks), out)
