"""Pretrained-weight import: torchvision-layout checkpoints -> graph params.

Capability parity with the reference's trained-model benchmark
(``ResNet50(weights="imagenet")``, reference test/test.py:13-14): a user
holding a standard ResNet50 checkpoint can deploy it on the pipeline.
"""

import numpy as np
import pytest

import jax

from defer_tpu.models.resnet import resnet, resnet50
from defer_tpu.utils.checkpoint import save_params
from defer_tpu.utils.pretrained import (convert_resnet50_state_dict,
                                        load_pretrained_resnet50,
                                        resnet50_torch_mapping)

DEPTHS = (1, 1)  # two bottleneck blocks: projection + identity paths


@pytest.fixture(scope="module")
def small():
    g = resnet(list(DEPTHS), width=8, num_classes=10, image_size=32,
               name="resnet_small")
    return g, g.init(jax.random.key(0))


def _synthetic_torch_sd(expected, depths):
    """Random state_dict in torchvision layout whose inverse-transformed
    values equal a reference pytree (so conversion is exactly checkable)."""
    rng = np.random.default_rng(0)
    mapping = resnet50_torch_mapping(depths)
    sd, truth = {}, {}
    inv = {(2, 3, 1, 0): (3, 2, 0, 1)}  # HWIO->OIHW inverse
    for (node, leaf), (src, tf) in mapping.items():
        want = np.shape(expected[node][leaf])
        val = rng.standard_normal(want).astype(np.float32)
        truth[(node, leaf)] = val
        if tf.__name__ == "_conv_t":
            sd[src] = np.transpose(val, inv[(2, 3, 1, 0)])
        elif tf.__name__ == "_fc_t":
            sd[src] = np.transpose(val, (1, 0))
        else:
            sd[src] = val
    return sd, truth


def test_convert_small_resnet_exact(small):
    g, expected = small
    sd, truth = _synthetic_torch_sd(expected, DEPTHS)
    params = convert_resnet50_state_dict(sd, expected, DEPTHS)
    # structure identical to graph.init's
    assert (jax.tree.structure(params) == jax.tree.structure(expected))
    for (node, leaf), val in truth.items():
        np.testing.assert_array_equal(params[node][leaf], val)
    # the converted params run
    y = jax.jit(g.apply)(params, np.zeros((1, 32, 32, 3), np.float32))
    assert y.shape == (1, 10)


def test_npz_and_flat_roundtrip(tmp_path, small):
    g, expected = small
    sd, truth = _synthetic_torch_sd(expected, DEPTHS)
    # torchvision-layout npz
    p = tmp_path / "ckpt.npz"
    np.savez(p, **sd)
    params = load_pretrained_resnet50(str(p), g, DEPTHS)
    np.testing.assert_array_equal(params["conv2d"]["w"],
                                  truth[("conv2d", "w")])
    # our own flat node/leaf layout (utils/checkpoint save format)
    p2 = tmp_path / "own.npz"
    save_params(str(p2), params)
    again = load_pretrained_resnet50(str(p2), g, DEPTHS)
    np.testing.assert_array_equal(again["predictions"]["b"],
                                  truth[("predictions", "b")])


def test_torch_pt_container(tmp_path, small):
    torch = pytest.importorskip("torch")
    g, expected = small
    sd, truth = _synthetic_torch_sd(expected, DEPTHS)
    p = tmp_path / "ckpt.pt"
    torch.save({k: torch.from_numpy(v) for k, v in sd.items()}, str(p))
    params = load_pretrained_resnet50(str(p), g, DEPTHS)
    np.testing.assert_array_equal(params["predictions"]["w"],
                                  truth[("predictions", "w")])


def test_missing_and_mismatched_fail_loudly(small):
    g, expected = small
    sd, _ = _synthetic_torch_sd(expected, DEPTHS)
    bad = dict(sd)
    del bad["conv1.weight"]
    with pytest.raises(ValueError, match="missing"):
        convert_resnet50_state_dict(bad, expected, DEPTHS)
    bad = dict(sd)
    bad["fc.weight"] = np.zeros((7, 7), np.float32)
    with pytest.raises(ValueError, match="mismatch"):
        convert_resnet50_state_dict(bad, expected, DEPTHS)


def test_full_resnet50_mapping_covers_every_leaf():
    """Shape contract against the real flagship model: the torchvision
    mapping addresses exactly the parametric leaves of resnet50()."""
    g = resnet50()
    expected = jax.eval_shape(lambda: g.init(jax.random.key(0)))
    mapping = resnet50_torch_mapping()
    addressed = set(mapping)
    parametric = {(node, leaf) for node, sub in expected.items()
                  for leaf in sub}
    assert addressed == parametric
    # standard torchvision key census: 53 convs + 53 bns * 4 + fc * 2
    assert len({src for src, _ in mapping.values()}) == 53 + 53 * 4 + 2
