"""Stage-interior profiling plane (obs/profile.py): ProfileSession
delta arithmetic, recompile episode discipline, memory-pressure
thresholds, the profile_start/profile_stop ctrl protocol (double-start
refused loudly), the phase-sum invariant on a live in-process chain,
and the monitor's DISP/DEV/MEM rendering."""

import io
import socket
import threading
import time
from contextlib import redirect_stdout

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu.obs import (LatencyHistogram, MemoryWatcher,
                           ProfileSession, RecompileWatcher, recorder)
from defer_tpu.obs.profile import NODE_PHASES, device_memory_bytes


def _recompile_events():
    return [e for e in recorder().snapshot() if e["kind"] == "recompile"]


def _mem_events():
    return [e for e in recorder().snapshot()
            if e["kind"] == "mem_pressure"]


# ---------------------------------------------------------------------------
# ProfileSession: window deltas over cumulative histograms
# ---------------------------------------------------------------------------

def test_profile_session_deltas_and_double_start():
    h = {"dispatch": LatencyHistogram(), "infer": LatencyHistogram()}
    h["dispatch"].record(0.010)
    h["infer"].record(0.015)              # pre-window traffic
    seen = [7]
    sess = ProfileSession(h, processed=lambda: seen[0])
    started = sess.start()
    assert started["t0_unix"] > 0
    with pytest.raises(RuntimeError, match="already started"):
        sess.start()
    for _ in range(4):
        h["dispatch"].record(0.002)
        h["infer"].record(0.003)
    seen[0] = 12
    rep = sess.stop()
    # the report prices the WINDOW, not the process lifetime
    assert rep["phases"]["dispatch"]["count"] == 4
    assert rep["phases"]["dispatch"]["sum_s"] == pytest.approx(
        0.008, rel=0.01)
    assert rep["phases"]["infer"]["mean_ms"] == pytest.approx(
        3.0, rel=0.01)
    assert rep["processed"] == 5
    assert rep["duration_s"] > 0
    assert rep["recompiles"] >= 0
    with pytest.raises(RuntimeError, match="never started"):
        sess.stop()


def test_profile_session_absent_phase_stays_honest():
    """A None histogram (e.g. an engine phase on a plain node) reports
    count 0 / mean None — never a fabricated number."""
    sess = ProfileSession({"gather": None})
    sess.start()
    rep = sess.stop()
    assert rep["phases"]["gather"] == {
        "count": 0, "sum_s": 0.0, "mean_ms": None, "p50_ms_cum": None}


# ---------------------------------------------------------------------------
# RecompileWatcher: counting always, ONE event per episode once armed
# ---------------------------------------------------------------------------

def test_recompile_wrap_episode_discipline():
    w = RecompileWatcher(episode_gap_s=0.2)
    calls = []
    f = w.wrap(lambda *a: calls.append(a), label="stage_fn")
    c0 = w.count
    ev0 = len(_recompile_events())
    # warmup signatures BEFORE arm: counted, silent
    f(np.zeros((2, 4), np.float32))
    f(np.zeros((2, 4), np.float32))       # repeat: cache hit, no count
    assert w.count - c0 == 1
    assert len(_recompile_events()) == ev0
    w.arm()
    # a burst of fresh signatures: every one counts, ONE event
    f(np.zeros((3, 4), np.float32))
    f(np.zeros((4, 4), np.float32))
    f(np.zeros((5, 4), np.float32))
    assert w.count - c0 == 4
    evs = _recompile_events()
    assert len(evs) == ev0 + 1
    assert evs[-1]["data"]["via"] == "wrap"
    assert evs[-1]["data"]["label"] == "stage_fn"
    assert evs[-1]["data"]["shapes"] == ["float32[3,4]"]
    # quiet >= episode_gap_s re-arms lazily: the next compile fires
    time.sleep(0.25)
    f(np.zeros((6, 4), np.float32))
    assert len(_recompile_events()) == ev0 + 2
    # disarm: counting continues, emission stops
    w.disarm()
    f(np.zeros((7, 4), np.float32))
    assert w.count - c0 == 6
    assert len(_recompile_events()) == ev0 + 2
    assert len(calls) == 7                # wrapping never eats calls


def test_recompile_monitoring_listener_counts_real_jit():
    """The jax.monitoring path: a fresh jit signature reaches XLA and
    is counted; the warm repeat is a program-cache hit and is NOT."""
    w = RecompileWatcher(episode_gap_s=60.0)
    w.install()
    f = jax.jit(lambda x: x * 2 + 1)
    f(jnp.ones((2, 3))).block_until_ready()   # make the jit exist
    c0 = w.count
    f(jnp.ones((2, 3))).block_until_ready()   # warm: cache hit
    assert w.count == c0
    f(jnp.ones((4, 3))).block_until_ready()   # fresh shape: compiles
    assert w.count > c0


# ---------------------------------------------------------------------------
# MemoryWatcher: gauge + threshold excursions with hysteresis
# ---------------------------------------------------------------------------

def test_memory_watcher_threshold_and_hysteresis():
    keep = jnp.ones((128,))               # ensure live bytes exist
    mw = MemoryWatcher()
    n0 = len(_mem_events())
    mw.set_threshold(1.0)                 # 1 byte: certainly exceeded
    n = mw.observe()
    assert n is not None and n > 1
    assert len(_mem_events()) == n0 + 1
    ev = _mem_events()[-1]["data"]
    assert ev["bytes"] == n and ev["threshold"] == 1
    assert ev["live_arrays"] >= 1
    # still over threshold: the excursion already fired, stays quiet
    mw.observe()
    assert len(_mem_events()) == n0 + 1
    # drop below 90% of a huge threshold -> re-arms, then fires again
    mw.set_threshold(1e15)
    mw.observe()
    mw.set_threshold(1.0)
    mw.observe()
    assert len(_mem_events()) == n0 + 2
    del keep


def test_memory_watcher_env_threshold(monkeypatch):
    mw = MemoryWatcher()
    monkeypatch.setenv("DEFER_MEM_PRESSURE_BYTES", "12345")
    assert mw.threshold_bytes() == 12345.0
    mw.set_threshold(99.0)                # explicit wins over env
    assert mw.threshold_bytes() == 99.0


def test_device_memory_bytes_counts_live_arrays():
    before = device_memory_bytes()
    assert before is not None             # jax imported in this test
    a = jnp.ones((1024,), jnp.float32)
    a.block_until_ready()
    after = device_memory_bytes()
    assert after >= before + 4096
    del a


# ---------------------------------------------------------------------------
# profile ctrl protocol: start/stop window, double-start refused loudly
# ---------------------------------------------------------------------------

def _profile_stub():
    from defer_tpu.runtime.node import LatencyHistogram as LH
    from defer_tpu.runtime.node import StageNode
    class _Prog:  # manifest carrier: the only prog attr ctrl reads
        manifest = {"index": 1, "name": "stage1"}

    node = StageNode.__new__(StageNode)
    node.prog = _Prog()
    node.codec = "raw"
    node.processed = 0
    node.reweights = 0
    node.address = ("127.0.0.1", 0)
    node._pending_trace = None
    node._merge = None
    node.infer_hist = LH()
    node.host_sync_hist = LH()
    node.disp_hist = LH()
    node.queue_hist = LH()
    node.dev_hist = LH()
    return node


def test_profile_ctrl_window_and_double_start():
    from defer_tpu.transport.framed import K_CTRL, recv_frame

    node = _profile_stub()
    a, b = socket.socketpair()
    try:
        assert node._handle_ctrl(a, {"cmd": "profile_start"})
        kind, rep = recv_frame(b)
        assert kind == K_CTRL and rep["cmd"] == "profile_started"
        assert rep["node"] == "stage1"
        # double start: loud refusal, session intact
        assert node._handle_ctrl(a, {"cmd": "profile_start"})
        kind, rep = recv_frame(b)
        assert rep["cmd"] == "profile_err"
        assert "already active" in rep["error"]
        assert node._profile is not None
        # traffic inside the window
        for _ in range(3):
            node.disp_hist.record(0.001)
            node.queue_hist.record(0.0005)
            node.dev_hist.record(0.002)
            node.host_sync_hist.record(0.0015)
            node.infer_hist.record(0.005)
        node.processed = 3
        assert node._handle_ctrl(a, {"cmd": "profile_stop"})
        kind, rep = recv_frame(b)
        assert rep["cmd"] == "profile_report"
        r = rep["report"]
        assert r["stage"] == 1 and r["node"] == "stage1"
        assert r["processed"] == 3
        for name in NODE_PHASES:
            assert r["phases"][name]["count"] == 3
        assert r["phases"]["infer"]["sum_s"] == pytest.approx(
            0.015, rel=0.01)
        # stop without a session: loud too
        assert node._handle_ctrl(a, {"cmd": "profile_stop"})
        kind, rep = recv_frame(b)
        assert rep["cmd"] == "profile_err"
        assert "no active profile session" in rep["error"]
    finally:
        a.close()
        b.close()


def test_stats_reply_carries_profile_telemetry():
    """The stats ctrl reply surfaces the phase histograms, the compile
    counter, live memory, and the session flag."""
    from defer_tpu.transport.framed import K_CTRL, recv_frame

    node = _profile_stub()
    node.disp_hist.record(0.004)
    node.queue_hist.record(0.001)
    node.dev_hist.record(0.006)
    a, b = socket.socketpair()
    try:
        assert node._handle_ctrl(a, {"cmd": "stats"})
        kind, rep = recv_frame(b)
        assert kind == K_CTRL
        assert rep["dispatch_s"]["count"] == 1
        assert rep["queue_s"]["count"] == 1
        assert rep["device_s"]["count"] == 1
        assert rep["dispatch_s"]["p50"] == pytest.approx(0.004, rel=0.5)
        assert rep["recompiles"] >= 0
        assert rep["mem_bytes"] is None or rep["mem_bytes"] >= 0
        assert rep["profiling"] is False
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# the phase-sum invariant on a real (in-process) chain
# ---------------------------------------------------------------------------

def test_phase_sums_tile_infer_on_live_chain():
    """dispatch + queue + device + host_sync must account for the
    issue-to-materialize infer wall on every stage of a streaming
    chain (the scripts/profile_smoke.py invariant, minimally)."""
    from defer_tpu import partition
    from defer_tpu.models import resnet_tiny
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    graph = resnet_tiny()
    params = graph.init(jax.random.key(0))
    stages = partition(graph, num_stages=2)
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(2)]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    for n in nodes:
        threading.Thread(target=n.serve, daemon=True).start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(stages, params, addrs, batch=2)
    try:
        xs = [np.random.default_rng(i).standard_normal(
            (2, 32, 32, 3)).astype(np.float32) for i in range(24)]
        disp.stream(xs[:4])               # compile
        disp.stream(xs)
        for node in nodes:
            inf = node.infer_hist.summary()
            parts = sum(h.summary().get("sum", 0.0)
                        for h in (node.disp_hist, node.queue_hist,
                                  node.dev_hist, node.host_sync_hist))
            assert inf["count"] >= 24
            assert parts == pytest.approx(inf["sum"], rel=0.15), (
                node.manifest["index"], parts, inf["sum"])
    finally:
        disp.close()


# ---------------------------------------------------------------------------
# monitor rendering: DISP/DEV/MEM columns, "-" at zero samples
# ---------------------------------------------------------------------------

def _row(stage, *, disp=None, dev=None, mem=None, recompiles=None):
    def ms(v):
        return ({"p50": v, "count": 10} if v is not None
                else {"p50": 0.0, "count": 0})
    return {"stage": stage, "replica": None, "branch": None, "join": 0,
            "tier": "tcp", "tier_fallbacks": 0,
            "throughput_per_s": 10.0, "processed": 100, "alive": True,
            "infer_ms": {"p50": 1.0, "p95": 1.2, "p99": 1.4},
            "host_sync_ms": ms(0.2),
            "dispatch_ms": ms(disp), "device_ms": ms(dev),
            "queue_ms": ms(None), "mem_bytes": mem,
            "recompiles": recompiles, "mfu": None,
            "pred_ms": None, "meas_ms": None, "err": None,
            "rx_q": 0, "tx_q": 0, "rx_hi": 0, "tx_hi": 0,
            "inflight": 0, "rx_bytes_per_s": 0.0,
            "tx_bytes_per_s": 0.0, "addr": f"127.0.0.1:{5000 + stage}"}


def test_monitor_renders_phase_columns_and_dash_when_absent():
    from defer_tpu.cli import _render_monitor

    rows = [_row(0, disp=0.5, dev=1.25, mem=2.5e6, recompiles=2),
            _row(1)]                      # no samples yet: all dashes
    buf = io.StringIO()
    with redirect_stdout(buf):
        _render_monitor(rows, None, [], {}, clear=False)
    out = buf.getvalue()
    assert "DISP" in out and "DEV" in out and "MEM" in out
    body = [ln for ln in out.splitlines()[1:] if ln.strip()]
    assert len(body) == 2
    assert "0.500" in body[0] and "1.250" in body[0]
    assert "2.5M" in body[0]
    # never fabricate: a node with zero phase samples renders "-" in
    # the DISP, DEV, and MEM columns (plus HS50's existing dash)
    cols = body[1].split()
    # STAGE BR REP TIER INF/S P50 P95 P99 HS50 DISP DEV MEM ...
    assert cols[9] == "-" and cols[10] == "-" and cols[11] == "-"
