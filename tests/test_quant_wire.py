"""Device-side int8 block-scale wire quantization.

Roundtrip precision of the quantizer itself, plus end-to-end: a pipeline
whose stage hops are int8-quantized in HBM must track the full-precision
model within the quantizer's error bound — the device-side equivalent of
the reference's lossy ZFP wire (src/node.py:107) without any host byte.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu import Defer, DeferConfig, SpmdPipeline, partition, pipeline_mesh
from defer_tpu.models import resnet_tiny
from defer_tpu.ops import (QUANT_BLOCK, dequantize_int8_blocks,
                           quantize_int8_blocks)


def test_quant_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 4 * QUANT_BLOCK)).astype(np.float32))
    q, s = quantize_int8_blocks(x)
    assert q.dtype == jnp.int8 and s.shape == (4, 4)
    y = dequantize_int8_blocks(q, s)
    blocks = np.asarray(x).reshape(4, 4, QUANT_BLOCK)
    bound = np.abs(blocks).max(-1, keepdims=True) / 127.0 * 0.5 + 1e-7
    err = np.abs(np.asarray(y).reshape(4, 4, QUANT_BLOCK) - blocks)
    assert (err <= bound + 1e-6).all()


def test_quant_handles_zeros_and_nonfinite():
    x = jnp.zeros((1, QUANT_BLOCK))
    q, s = quantize_int8_blocks(x)
    assert np.asarray(dequantize_int8_blocks(q, s)).max() == 0.0
    x = jnp.full((1, QUANT_BLOCK), jnp.inf)
    q, s = quantize_int8_blocks(x)
    assert np.isfinite(np.asarray(dequantize_int8_blocks(q, s))).all()


def test_quant_rejects_ragged():
    with pytest.raises(ValueError, match="multiple"):
        quantize_int8_blocks(jnp.zeros((2, QUANT_BLOCK + 1)))


def test_pipeline_int8_wire_tracks_full_precision():
    g = resnet_tiny()
    p = g.init(jax.random.key(0))
    x = np.random.default_rng(1).normal(
        size=(4, 1, 32, 32, 3)).astype(np.float32)
    ref = np.stack([np.asarray(jax.jit(g.apply)(p, xi)) for xi in x])

    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, p, mesh=pipeline_mesh(4), microbatch=1,
                        chunk=4, wire="int8")
    assert pipe.buf_elems % QUANT_BLOCK == 0
    # hop bytes ~= elems (int8) + scales, < half the f32 buffer bytes
    assert pipe.metrics.buffer_bytes_per_hop < 2 * pipe.buf_elems
    out = pipe.run(x)
    # lossy wire: close but not bit-exact
    assert np.abs(out - ref).max() < 0.15
    assert np.square(out - ref).mean() < 1e-3
    # top-1 class preserved
    assert (out.argmax(-1) == ref.argmax(-1)).all()


def test_defer_api_wire_option():
    g = resnet_tiny()
    p = g.init(jax.random.key(0))
    x = np.zeros((2, 1, 32, 32, 3), np.float32)
    out = Defer(config=DeferConfig(microbatch=1, chunk=2, wire="int8")).run(
        g, p, x, num_stages=2)
    assert out.shape == (2, 1, 10)


def test_pallas_quant_kernel_matches_jnp():
    """One implementation contract: the Pallas kernel (interpret mode on
    CPU) and the jnp reference produce identical payloads and scales."""
    from defer_tpu.ops.quant import quantize_int8_blocks
    from defer_tpu.ops.quant_pallas import quantize_int8_blocks_pallas

    rng = np.random.default_rng(0)
    for shape in [(4, 2048), (2, 3, 512), (1, 256)]:
        x = jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 50)
        qr, sr = quantize_int8_blocks(x, use_pallas=False)
        qp, sp = quantize_int8_blocks_pallas(x, interpret=True)
        np.testing.assert_array_equal(np.asarray(qr), np.asarray(qp))
        np.testing.assert_allclose(np.asarray(sr), np.asarray(sp),
                                   rtol=1e-7)
    # non-finite flush behavior matches too
    x = jnp.asarray([[np.inf, -np.inf, np.nan] + [1.0] * 253], np.float32)
    qr, sr = quantize_int8_blocks(x, use_pallas=False)
    qp, sp = quantize_int8_blocks_pallas(x, interpret=True)
    np.testing.assert_array_equal(np.asarray(qr), np.asarray(qp))
