"""The seq-replay substrate (docs/ROBUSTNESS.md): retain-until-ack
windows, replay-tolerant fan-in dedup, channel healing over real
sockets, per-stage quiesce, and the live-replan cutover.

The correctness claim under test is byte-identity: a stream that
crosses a failover or a mid-stream replan must equal the undisturbed
run bit for bit — no dropped, duplicated, or reordered frame.  The
unit layers here pin the properties that claim reduces to.
"""

import queue
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.obs.events import recorder
from defer_tpu.plan.cost import StageCostModel
from defer_tpu.plan.replan import LiveReplan, replan
from defer_tpu.plan.solver import solve
from defer_tpu.runtime.node import ChainDispatcher, StageNode
from defer_tpu.serve import poisson_trace
from defer_tpu.transport.framed import (K_CTRL, K_END, recv_frame,
                                        send_ctrl)
from defer_tpu.transport.replay import ReplayBuffer, ReplayFanOut
from defer_tpu.transport.replicate import FanInMerge


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


# ---------------------------------------------------------------------------
# ReplayBuffer: the bounded retain-until-ack window
# ---------------------------------------------------------------------------

def test_replay_buffer_retain_ack_release():
    b = ReplayBuffer(8)
    for s in range(5):
        b.retain(s, f"f{s}")
    assert b.depth() == 5 and b.hi == 5
    assert b.unacked() == [(s, f"f{s}") for s in range(5)]
    b.ack(3)  # cumulative: 0..2 released
    assert b.depth() == 2
    assert [s for s, _ in b.unacked()] == [3, 4]
    b.ack(1)  # stale ack: no-op (acks race across R relay paths)
    assert b.depth() == 2 and b.acked == 3
    b.retain(2, "late")  # already-acked seq: no-op
    assert b.depth() == 2


def test_replay_buffer_full_window_blocks_until_ack():
    b = ReplayBuffer(2)
    b.retain(0, "a")
    b.retain(1, "b")
    parked = threading.Event()
    done = threading.Event()

    def producer():
        parked.set()
        b.retain(2, "c", timeout=30.0)
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    parked.wait(5.0)
    time.sleep(0.2)
    assert not done.is_set(), "full window must backpressure the sender"
    b.ack(1)
    t.join(timeout=10)
    assert done.is_set()
    with pytest.raises(TimeoutError, match="replay window full"):
        b.retain(3, "d", timeout=0.1)


def test_replay_buffer_fail_wakes_parked_producer():
    b = ReplayBuffer(1)
    b.retain(0, "a")
    errs: list = []

    def producer():
        try:
            b.retain(1, "b", timeout=30.0)
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.1)
    b.fail(ConnectionError("replica gone"))
    t.join(timeout=10)
    assert errs and isinstance(errs[0], ConnectionError)


# ---------------------------------------------------------------------------
# FanInMerge under replay: dedup inside the window
# ---------------------------------------------------------------------------

def test_merge_dedups_replay_overlap_inside_window():
    """A healed fan-out replays frames its acks had not covered; the
    merge absorbs the overlap silently and the released stream is
    untouched."""
    m = FanInMerge(1, capacity=8, replay_window=4)
    for s in range(4):
        m.put(s, f"v{s}")
    got = [m.get(1.0)[1] for _ in range(4)]
    # failover replay: frames 2, 3 re-arrive (already merged)
    m.put(2, "v2")
    m.put(3, "v3")
    m.put(4, "v4")
    assert m.get(1.0)[1] == "v4"
    assert got == ["v0", "v1", "v2", "v3"]
    assert m.duplicates == 2


def test_merge_replay_window_still_rejects_ancient_seqs():
    """The window is a tolerance, not amnesia: a seq older than the
    window behind the head is a protocol violation, not a replay."""
    m = FanInMerge(1, capacity=8, replay_window=2)
    for s in range(5):
        m.put(s, s)
        m.get(1.0)
    m.put(3, 3)  # inside the window: absorbed
    assert m.duplicates == 1
    with pytest.raises(ValueError, match="duplicate/stale"):
        m.put(0, 0)  # 0 < next(5) - window(2): ancient


def test_merge_strict_mode_unchanged_without_window():
    m = FanInMerge(1, capacity=8)
    m.put(0, "a")
    m.get(1.0)
    with pytest.raises(ValueError, match="duplicate/stale"):
        m.put(0, "a")


def test_merge_dedup_property_random_replay_overlaps():
    """Property test: for random streams with random failover-replay
    overlaps (any slice of the trailing window, re-put at any point),
    the released stream is exactly 0..N-1 in order and every overlap
    frame is counted as a duplicate."""
    rng = np.random.default_rng(42)
    for trial in range(25):
        window = int(rng.integers(1, 9))
        n = int(rng.integers(10, 50))
        m = FanInMerge(1, capacity=64, replay_window=window)
        out: list = []
        dups = 0
        for s in range(n):
            m.put(s, s)
            if rng.random() < 0.35:
                # replay a random slice of the last `window` positions
                lo = max(0, s + 1 - window)
                start = int(rng.integers(lo, s + 1))
                for r in range(start, s + 1):
                    m.put(r, r)
                    dups += 1
            while True:
                try:
                    out.append(m.get_nowait()[1])
                except queue.Empty:
                    break
        m.end()
        while True:
            try:
                kind, v = m.get_nowait()
            except queue.Empty:
                break
            if kind == K_END:
                break
            out.append(v)
        assert out == list(range(n)), \
            f"trial {trial} (window={window}): stream corrupted"
        assert m.duplicates == dups, f"trial {trial}"


# ---------------------------------------------------------------------------
# ReplayFanOut: heal over real sockets
# ---------------------------------------------------------------------------

def _replica_reader(conn, frames: list, stop_after: int | None = None):
    """Minimal fan-in stand-in: record seq-stamped frames; on END play
    the clean-shutdown half of the ack protocol (final cumulative ack +
    ``replay_done``); with ``stop_after``, stop early instead — the
    caller closes the socket to simulate death mid-stream."""
    try:
        while True:
            kind, value = recv_frame(conn)
            if kind == K_END:
                if frames:
                    hi = max(int(seq) for seq, _ in frames) + 1
                    send_ctrl(conn, {"cmd": "replay_ack", "seq": hi})
                send_ctrl(conn, {"cmd": "replay_done"})
                return
            if kind == K_CTRL:
                continue
            frames.append(value)
            if stop_after is not None and len(frames) >= stop_after:
                return
    except (OSError, ConnectionError):
        pass


def test_fanout_heals_dead_channel_and_replays_unacked():
    """Single-channel fan-out against a replica that acks part of its
    window and then dies: the heal redials the same address, re-sends
    the preamble, replays exactly the unacked frames, and later sends
    continue on the new connection."""
    srv = socket.create_server(("127.0.0.1", 0))
    srv.settimeout(30.0)
    port = srv.getsockname()[1]
    s = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    conn1, _ = srv.accept()
    fo = ReplayFanOut([s], [("127.0.0.1", port)], window=64,
                      redial_timeout_s=15.0, replay_gauge=None)
    second: list = []
    accepted2 = threading.Event()

    def acceptor2():
        conn2, _ = srv.accept()
        accepted2.set()
        _replica_reader(conn2, second)
        conn2.close()

    t2 = threading.Thread(target=acceptor2, daemon=True)
    try:
        fo.send_ctrl({"cmd": "stream_begin", "stage": 0})
        xs = [np.full((2,), i, np.float32) for i in range(10)]
        for x in xs[:4]:
            fo.send(x)
        fo.flush(timeout=10.0)
        first: list = []
        rt = threading.Thread(target=_replica_reader,
                              args=(conn1, first, 4), daemon=True)
        rt.start()
        rt.join(timeout=10)
        assert len(first) == 4
        # ack frames 0, 1 then die without replay_done
        send_ctrl(conn1, {"cmd": "replay_ack", "seq": 2})
        deadline = time.monotonic() + 10
        while fo.replay_depth() > 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fo.replay_depth() == 2
        t2.start()
        conn1.close()  # replica death: EOF on the ack reader
        assert accepted2.wait(20.0), "heal never redialed"
        for x in xs[4:]:
            fo.send(x)
        fo.send_end()
        fo.close(timeout=15.0)
        # close() joins the SEND side; the reader thread may still be
        # draining buffered frames — join it before asserting on them
        t2.join(timeout=10.0)
        assert not t2.is_alive(), "replica reader never saw END"
        assert fo.failovers == 1
        # new connection saw the replayed window (2, 3) then the rest,
        # stamped with their original seqs — no renumbering.  The wire
        # MAY carry one duplicate of the first post-heal frame: when
        # send(4) is what detects the dead channel, frame 4 is already
        # retained when the heal snapshots unacked(), so the heal
        # replays it AND the send retry re-sends it (the documented
        # contract — ReplayFanOut.send(): the downstream merge dedups
        # inside its replay window).  Dedup like the merge does;
        # order must still be non-decreasing originals.
        seqs = [int(seq) for seq, _ in second]
        deduped: list = []
        for q in seqs:
            if deduped and q == deduped[-1]:
                continue            # heal-vs-retry duplicate
            assert not deduped or q > deduped[-1], \
                f"out-of-order replay: {seqs}"
            deduped.append(q)
        assert deduped == [2, 3] + list(range(4, 10))
        for seq, arr in second:
            np.testing.assert_array_equal(arr, xs[int(seq)])
        evs = [e for e in recorder().snapshot()
               if e["kind"] == "failover"
               and e["data"].get("addr") == f"127.0.0.1:{port}"]
        # replayed = the unacked window at snapshot time: frames 2, 3
        # plus frame 4 iff the racing send retained it first
        assert evs and evs[-1]["data"]["replayed"] in (2, 3)
        assert evs[-1]["data"]["recovery_ms"] > 0
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# quiesce + live replan (persist-mode in-process chain)
# ---------------------------------------------------------------------------

def _boot_persist_chain(n: int):
    nodes = [StageNode(None, "127.0.0.1:0", None, persist=True)
             for _ in range(n)]
    addrs = [f"127.0.0.1:{node.address[1]}" for node in nodes]
    threads = [threading.Thread(target=node.serve, daemon=True)
               for node in nodes]
    for t in threads:
        t.start()
    return addrs, threads


def test_quiesce_returns_stable_sequence_points(tiny):
    g, params = tiny
    addrs, threads = _boot_persist_chain(2)
    disp = ChainDispatcher(addrs[0], codec="raw")
    try:
        disp.deploy(partition(g, num_stages=2), params, addrs, batch=1)
        rng = np.random.default_rng(3)
        xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
              for _ in range(3)]
        outs = disp.stream(xs)
        assert len(outs) == 3
        processed = disp.quiesce(addrs, timeout_s=30.0)
        assert processed == [3, 3], \
            "every stage must quiesce at the same stream position"
        evs = [e for e in recorder().snapshot() if e["kind"] == "quiesce"]
        assert len(evs) >= 2
    finally:
        disp.end_stream()
        disp.shutdown_nodes(addrs)
        disp.close()
        for t in threads:
            t.join(timeout=30)


def test_live_replan_cutover_byte_identical_under_bursty_arrivals(tiny):
    """The acceptance path end to end: a bursty arrival trace feeds a
    persist chain, measured telemetry drives the straggler/replanner
    suggestion, ``ReplanResult.apply`` cuts the chain over mid-stream,
    and the full output stream is byte-identical to two undisturbed
    runs (old cuts then new cuts) over the same inputs."""
    g, params = tiny
    cost = StageCostModel(g)
    plan1 = solve(g, 3, cost)
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(10)]
    cut = 6
    # open-loop bursty arrivals for segment 1 (compressed to keep the
    # test fast: the point is jittered, bursty feed timing, not wall
    # time)
    offsets = poisson_trace(200.0, 1.0, seed=5,
                            bursts=[(0.2, 0.5, 3.0)])[:cut]
    while len(offsets) < cut:
        offsets.append(offsets[-1] if offsets else 0.0)

    addrs, threads = _boot_persist_chain(3)
    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(partition(g, list(plan1.cuts)), params, addrs, batch=1)
    live = LiveReplan(disp, g, params, addrs, batch=1)

    def bursty(inputs):
        t0 = time.monotonic()
        for off, x in zip(offsets, inputs):
            lag = t0 + off * 0.2 - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            yield x

    outs = disp.stream(bursty(xs[:cut]))
    # the straggler path: measured per-stage seconds showing stage 0
    # hot drive the replanner's suggestion; its apply() performs the
    # cutover only when the suggestion actually moves the cuts
    measured = {0: 0.5, 1: 0.001, 2: 0.001}
    result = replan(g, plan1, measured, cost)
    assert result.moved, "a 500x stage-0 correction must move the cuts"
    receipt = result.apply(live, min_improvement=1.0)
    assert receipt is not None
    assert receipt["stages"] == 3
    assert receipt["quiesced"] == [cut, cut, cut]
    outs += disp.stream(xs[cut:])
    disp.close()
    live.shutdown()
    for t in threads:
        t.join(timeout=30)
    assert live.cutovers == 1
    evs = [e for e in recorder().snapshot() if e["kind"] == "cutover"]
    assert evs and evs[-1]["data"]["stages"] == 3

    def plain(cuts, inputs):
        nodes = [StageNode(None, "127.0.0.1:0", None) for _ in range(3)]
        p_addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
        ths = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
        for t in ths:
            t.start()
        d = ChainDispatcher(p_addrs[0], codec="raw")
        d.deploy(partition(g, list(cuts)), params, p_addrs, batch=1)
        got = d.stream(inputs)
        d.close()
        for t in ths:
            t.join(timeout=30)
        return got

    ref = plain(plan1.cuts, xs[:cut]) \
        + plain(result.new_plan.cuts, xs[cut:])
    assert len(outs) == len(ref) == len(xs)
    for i, (y, r) in enumerate(zip(outs, ref)):
        np.testing.assert_array_equal(y, r, err_msg=f"sample {i}")


def test_replan_apply_skips_unmoved_suggestions(tiny):
    g, params = tiny
    cost = StageCostModel(g)
    plan1 = solve(g, 3, cost)
    # telemetry that matches the prediction: nothing should move
    result = replan(g, plan1, {}, cost)
    assert not result.moved

    class _Boom:
        def apply(self, *_a, **_k):  # pragma: no cover
            raise AssertionError("unmoved suggestion must not cut over")

    assert result.apply(_Boom()) is None


# ---------------------------------------------------------------------------
# front door: chain backend dies mid-request
# ---------------------------------------------------------------------------

class _DyingDispatcher:
    """A chain whose send path dies on first use (the in-process twin
    of a killed chain): recv_result stays silent, send raises."""

    codec = "raw"

    def __init__(self):
        self.sent = 0

    def begin_trace(self, **_kw):
        pass

    def send_request_frame(self, *_a, **_kw):
        self.sent += 1
        raise ConnectionError("chain backend died")

    def recv_result(self, timeout_s=1.0):
        time.sleep(min(timeout_s, 0.05))
        raise TimeoutError

    def close(self):
        pass


def test_frontdoor_backend_lost_sheds_and_settles_exactly_once():
    """A chain backend dying mid-request must shed every affected
    tenant with retry_after_ms, settle each admission slot exactly
    once, emit backend_lost, and keep the door answering (degraded)
    instead of failing the healthcheck."""
    from defer_tpu.serve import ServeClient
    from defer_tpu.serve.frontdoor import ChainBackend, ServeFrontDoor

    backend = ChainBackend(_DyingDispatcher(), 2, (4,))
    door = ServeFrontDoor(backend=backend, seed_service_s=0.01).start()
    host, port = door.address
    try:
        c = ServeClient(host, port, "victim")
        res = c.stream([np.zeros(4, np.float32) for _ in range(3)])
        assert len(res) == 3
        kinds = {k for k, *_ in res}
        assert kinds == {"shed"}, \
            "every in-flight unit must come back as a shed, not hang"
        for _k, msg, _t in res:
            assert msg["reason"] == "backend_lost"
            assert msg["retry_after_ms"] > 0
        # slots settled exactly once: nothing left admitted
        assert door.admission.inflight == 0
        assert door.admission.queue.qsize() == 0
        # degraded, not dead: the healthcheck passes and the state is
        # visible in the pressure snapshot instead
        door.healthcheck()
        assert door.stats()["pressure"]["backend_lost"] is True
        # the sweep emits the event just after releasing the last
        # client; give its thread a beat
        deadline = time.monotonic() + 5.0
        evs: list = []
        while not evs and time.monotonic() < deadline:
            evs = [e for e in recorder().snapshot()
                   if e["kind"] == "backend_lost"]
            if not evs:
                time.sleep(0.02)
        assert evs and evs[-1]["data"]["shed"] >= 1
        # a NEW sample after the loss sheds at ingest with the same
        # contract — the door never admits into a dead chain
        c2 = ServeClient(host, port, "late")
        res2 = c2.stream([np.zeros(4, np.float32)])
        assert res2[0][0] == "shed"
        assert res2[0][1]["reason"] == "backend_lost"
        assert door.admission.inflight == 0
    finally:
        door.stop()


# ---------------------------------------------------------------------------
# kill -9 failover, full multi-process chain (slow)
# ---------------------------------------------------------------------------

_KILL_SCRIPT = r"""
import os, signal, sys, threading, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.runtime.node import run_chain

g = resnet_tiny()
params = g.init(jax.random.key(0))
stages = partition(g, num_stages=3)
rng = np.random.default_rng(0)
xs = [rng.standard_normal((1,) + stages[0].in_spec.shape)
      .astype(np.float32) for _ in range(16)]
started = threading.Event()

def feeder():
    for i, x in enumerate(xs):
        if i == 6:
            started.set()
        yield x

def on_spawn(procs):
    def killer():
        started.wait(180)
        time.sleep(0.3)
        procs[1].send_signal(signal.SIGKILL)  # stage 1, replica 0
    threading.Thread(target=killer, daemon=True).start()

outs = run_chain(stages, params, feeder(), batch=1, replicas={1: 2},
                 failover=True, on_spawn=on_spawn,
                 artifact_dir=sys.argv[1],
                 stage_delays=[0.0, 0.4, 0.0])
ref = run_chain(stages, params, list(xs), batch=1,
                artifact_dir=sys.argv[1])
assert len(outs) == len(ref) == len(xs), (len(outs), len(ref))
for a, b in zip(outs, ref):
    np.testing.assert_array_equal(a, b)
print("BYTE-IDENTICAL", len(outs))
"""


@pytest.mark.slow
def test_kill9_replica_failover_byte_identical(tmp_path):
    """kill -9 a mid-chain replica while the stream is in flight: the
    supervisor respawns it, the fan-out heals + replays, and the output
    equals the undisturbed run byte for byte."""
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT, str(tmp_path)],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "BYTE-IDENTICAL 16" in proc.stdout
