"""Stage replication: the ordered fan-out/fan-in transport layer
(``transport/replicate.py``), the replicated chain runtime, and the
hardened ``run_chain`` failure paths.

The reorder-buffer unit tests pin the merge's contract — strict sequence
order, gap stalls, bounded-buffer backpressure, duplicate/stale
rejection, R-upstream END bookkeeping — because the runtime's
correctness claim ("replicated chain output is byte-identical to the
serial chain") reduces to exactly those properties.
"""

import queue
import socket
import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.transport.framed import (K_CTRL, K_END, K_TENSOR,
                                        K_TENSOR_SEQ, recv_frame,
                                        send_frame)
from defer_tpu.transport.replicate import FanInMerge, FanOutSender

#: stage-node subprocesses must never touch the (single-client) TPU tunnel
CPU_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


# ---------------------------------------------------------------------------
# sequence-stamped frames (protocol v2)
# ---------------------------------------------------------------------------

def test_seq_frame_round_trip():
    a, b = socket.socketpair()
    try:
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        send_frame(a, arr, seq=7, codec="bf8")
        kind, value = recv_frame(b)
        assert kind == K_TENSOR_SEQ
        seq, got = value
        assert seq == 7 and got.shape == (3, 4)
        # plain frames are untouched by the v2 addition
        send_frame(a, arr)
        kind, got = recv_frame(b)
        assert kind == K_TENSOR and got.shape == (3, 4)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# reorder buffer
# ---------------------------------------------------------------------------

def test_merge_gap_stalls_consumer_until_filled():
    """Later frames buffered, the next-needed one missing: the consumer
    must PARK (never reorder silently), then release everything in
    order once the gap fills."""
    m = FanInMerge(2, capacity=8)
    m.put(1, "b")
    m.put(2, "c")
    with pytest.raises(queue.Empty):
        m.get_nowait()
    with pytest.raises(TimeoutError):
        m.get(timeout=0.2)
    m.put(0, "a")
    assert [m.get(1.0)[1] for _ in range(3)] == ["a", "b", "c"]


def test_merge_backpressure_parks_producer_but_admits_needed_seq():
    """A full buffer of future frames blocks further out-of-order puts
    (backpressure toward the fast replica) — but the sequence the
    consumer is parked on is ALWAYS admitted, so a full buffer can
    never deadlock the stream."""
    m = FanInMerge(2, capacity=2)
    m.put(1, "b")
    m.put(2, "c")  # buffer full: {1, 2}
    blocked = threading.Event()
    unblocked = threading.Event()

    def slow_path():
        blocked.set()
        m.put(3, "d", timeout=30.0)  # must park: buffer full, not needed
        unblocked.set()

    t = threading.Thread(target=slow_path, daemon=True)
    t.start()
    blocked.wait(5.0)
    time.sleep(0.2)
    assert not unblocked.is_set()      # producer parked on the full buffer
    m.put(0, "a")                      # the needed seq is admitted anyway
    assert m.get(1.0)[1] == "a"        # back AT capacity: still parked
    time.sleep(0.2)
    assert not unblocked.is_set()
    assert m.get(1.0)[1] == "b"        # below capacity: producer wakes
    t.join(timeout=10)
    assert unblocked.is_set()
    assert [m.get(1.0)[1] for _ in range(2)] == ["c", "d"]


def test_merge_rejects_duplicate_and_stale_seq():
    m = FanInMerge(2, capacity=4)
    m.put(0, "a")
    with pytest.raises(ValueError, match="duplicate/stale"):
        m.put(0, "dup")            # duplicate while buffered
    assert m.get(1.0) == (K_TENSOR, "a")
    with pytest.raises(ValueError, match="duplicate/stale"):
        m.put(0, "late")           # stale: already released
    m.put(1, "b")
    assert m.get(1.0)[1] == "b"


def test_merge_end_requires_all_upstreams():
    """K_END with R upstreams: one END is not the stream's end; R are.
    Interleaving END with still-buffered frames must drain in order
    first."""
    m = FanInMerge(3, capacity=8)
    m.put(0, "a")
    m.end()                         # upstream 0 done
    m.end()                         # upstream 1 done
    assert m.get(1.0)[1] == "a"
    with pytest.raises(TimeoutError):
        m.get(timeout=0.2)          # 2 of 3 ENDs: not over yet
    m.put(1, "b")                   # upstream 2 still streaming
    m.end()
    assert m.get(1.0)[1] == "b"
    assert m.get(1.0) == (K_END, None)


def test_merge_end_with_gap_raises():
    """All upstreams ended but a sequence slot never arrived (a replica
    died between fan-out and fan-in): loud, never a silent skip."""
    m = FanInMerge(2, capacity=8)
    m.put(1, "b")
    m.end()
    m.end()
    with pytest.raises(ConnectionError, match="gap"):
        m.get(timeout=1.0)


def test_merge_ctrl_rides_ahead_and_reader_failure_propagates():
    m = FanInMerge(2, capacity=4)
    m.put(0, "a")
    m.put_ctrl({"cmd": "trace"})
    kind, msg = m.get(1.0)
    assert kind == K_CTRL and msg["cmd"] == "trace"
    assert m.get(1.0)[1] == "a"
    m.fail(ConnectionError("replica died"))
    with pytest.raises(ConnectionError, match="replica died"):
        m.get(timeout=1.0)
    with pytest.raises(ConnectionError, match="replica died"):
        m.put(1, "b")


def test_merge_capacity_validation():
    with pytest.raises(ValueError, match="capacity"):
        FanInMerge(4, capacity=2)
    with pytest.raises(ValueError, match="expected"):
        FanInMerge(0)


# ---------------------------------------------------------------------------
# fan-out sender
# ---------------------------------------------------------------------------

def test_fanout_round_robin_stamps_sequence():
    """Tensor i goes to channel i % R carrying seq i; ctrl and END
    broadcast to every channel."""
    pairs = [socket.socketpair() for _ in range(2)]
    try:
        fo = FanOutSender([a for a, _ in pairs], depth=4)
        for i in range(6):
            fo.send(np.full((2,), i, np.int32))
        fo.close(timeout=10.0)
        for r, (_, b) in enumerate(pairs):
            seqs = []
            while True:
                kind, value = recv_frame(b)
                if kind == K_END:
                    break
                assert kind == K_TENSOR_SEQ
                seq, arr = value
                assert int(arr[0]) == seq  # payload i carries seq i
                seqs.append(seq)
            assert seqs == [r, r + 2, r + 4]
    finally:
        for a, b in pairs:
            a.close()
            b.close()


def test_fanout_merge_round_trip_out_of_order_arrival():
    """End to end through real sockets: fan out 2 ways, merge back — in
    order, even when one path's reader runs far behind."""
    pairs = [socket.socketpair() for _ in range(2)]
    try:
        fo = FanOutSender([a for a, _ in pairs], depth=8)
        merge = FanInMerge(2, capacity=8)

        def reader(r, delay):
            sock = pairs[r][1]
            try:
                while True:
                    kind, value = recv_frame(sock)
                    if kind == K_END:
                        merge.end()
                        return
                    if kind == K_CTRL:
                        continue
                    time.sleep(delay)  # one slow replica path
                    merge.put(*value)
            except BaseException as e:  # noqa: BLE001
                merge.fail(e)

        threads = [threading.Thread(target=reader, args=(r, 0.02 * r),
                                    daemon=True) for r in range(2)]
        for t in threads:
            t.start()
        n = 12
        for i in range(n):
            fo.send(np.full((2,), i, np.int32))
        fo.close(timeout=10.0)
        got = []
        while True:
            kind, value = merge.get(timeout=30.0)
            if kind == K_END:
                break
            got.append(int(value[0]))
        assert got == list(range(n))
        for t in threads:
            t.join(timeout=10)
    finally:
        for a, b in pairs:
            a.close()
            b.close()


# ---------------------------------------------------------------------------
# replicated chain runtime (in-process thread nodes)
# ---------------------------------------------------------------------------

def _run_chain_inproc(stages, params, xs, *, replicas=(1, 1, 1),
                      codecs=None):
    from defer_tpu.runtime.node import ChainDispatcher, StageNode

    groups = []
    for k, r in enumerate(replicas):
        fan_in = replicas[k - 1] if k > 0 else 1
        groups.append([
            StageNode(None, "127.0.0.1:0", None,
                      replica=j if r > 1 else None, fan_in=fan_in)
            for j in range(r)])
    addr_groups = [[f"127.0.0.1:{n.address[1]}" for n in grp]
                   for grp in groups]
    flat = [n for grp in groups for n in grp]
    threads = [threading.Thread(target=n.serve, daemon=True) for n in flat]
    for t in threads:
        t.start()
    disp = ChainDispatcher(",".join(addr_groups[0]), codec="raw",
                           result_fan_in=replicas[-1])
    try:
        disp.deploy(stages, params, addr_groups, batch=xs[0].shape[0],
                    codecs=codecs)
        outs = disp.stream(xs)
        stats = disp.stats([a for grp in addr_groups for a in grp])
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=30)
    return outs, stats


def test_replicated_chain_byte_identical_and_split(tiny):
    """Replicating the middle stage is a scheduling change only: same
    outputs, same order, and the round-robin split is visible in the
    per-replica stats."""
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(21)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(6)]
    base, _ = _run_chain_inproc(stages, params, xs)
    rep, stats = _run_chain_inproc(stages, params, xs,
                                   replicas=(1, 2, 1))
    assert len(base) == len(rep) == 6
    for a, b in zip(base, rep):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    per_rep = {s["replica"]: s["processed"] for s in stats
               if s.get("stage") == 1}
    assert per_rep == {0: 3, 1: 3}
    fan_in = [s["fan_in"] for s in stats]
    assert fan_in == [1, 1, 1, 2]


def test_replicated_last_stage_and_short_stream(tiny):
    """Last-stage replication (dispatcher-side fan-in merge), including
    the fewer-inputs-than-replicas edge where one replica only ever
    sees the cascaded END."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    rng = np.random.default_rng(22)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(3)]
    base, _ = _run_chain_inproc(stages, params, xs, replicas=(1, 1))
    rep, _ = _run_chain_inproc(stages, params, xs, replicas=(1, 2))
    for a, b in zip(base, rep):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    one, _ = _run_chain_inproc(stages, params, xs[:1], replicas=(1, 2))
    np.testing.assert_array_equal(np.asarray(one[0]), np.asarray(base[0]))


def test_adjacent_replication_rejected(tiny):
    from defer_tpu.runtime.node import _normalize_replicas
    with pytest.raises(ValueError, match="adjacent"):
        _normalize_replicas({0: 2, 1: 2}, 3)
    with pytest.raises(ValueError, match="out of range"):
        _normalize_replicas({7: 2}, 3)
    assert _normalize_replicas({1: 3}, 3) == [1, 3, 1]


# ---------------------------------------------------------------------------
# run_chain failure hardening (satellites: bind-race retry, kill-mid-stream)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_run_chain_kills_children_when_one_dies_mid_stream(tiny):
    """Kill one node mid-stream: run_chain must raise (with the dead
    node attributed) and terminate every remaining child before the
    error propagates — no leaked replica processes."""
    from defer_tpu.runtime.node import run_chain

    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(23)
    spawned: list = []

    def on_spawn(procs):
        spawned.extend(procs)

    def inputs():
        # feed a couple of frames, murder the middle node, keep feeding
        for i in range(40):
            if i == 2:
                spawned[1].kill()
            yield rng.standard_normal((1, 32, 32, 3)).astype(np.float32)

    with pytest.raises(RuntimeError, match="stage1"):
        run_chain(stages, params, inputs(), env=CPU_ENV,
                  on_spawn=on_spawn, spawn_retries=1)
    assert len(spawned) == 3
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if all(pr.poll() is not None for pr in spawned):
            break
        time.sleep(0.2)
    assert all(pr.poll() is not None for pr in spawned), (
        "run_chain leaked live children: "
        f"{[pr.poll() for pr in spawned]}")


@pytest.mark.slow
def test_run_chain_retries_bind_race(tiny, monkeypatch):
    """Steal one probed port before the children spawn: attempt 1 dies
    with address-in-use, the retry on fresh ports succeeds."""
    from defer_tpu.runtime import node as node_mod

    g, params = tiny
    stages = partition(g, num_stages=2)
    rng = np.random.default_rng(24)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(2)]
    real_free_ports = node_mod._free_ports
    thief: list = []

    def stealing_free_ports(n):
        ports = real_free_ports(n)
        if not thief:  # first attempt only: occupy a node's port
            thief.append(socket.create_server(("127.0.0.1", ports[0])))
        return ports

    monkeypatch.setattr(node_mod, "_free_ports", stealing_free_ports)
    try:
        outs = node_mod.run_chain(stages, params, xs, env=CPU_ENV,
                                  spawn_retries=3)
        assert len(outs) == 2
    finally:
        for s in thief:
            s.close()


@pytest.mark.slow
def test_three_process_replicated_chain_matches_single_program(tiny):
    """Full multi-process topology: stage 1 as two OS-process replicas,
    against the single-program oracle, with per-replica stats."""
    from defer_tpu.runtime.node import run_chain

    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(25)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    stats: list = []
    outs = run_chain(stages, params, xs, env=CPU_ENV,
                     replicas={1: 2}, stats_out=stats)
    assert len(outs) == 5
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(
            y, np.asarray(fwd(params, x)), rtol=2e-4, atol=2e-4)
    per_rep = {s["replica"]: s["processed"] for s in stats
               if s.get("stage") == 1}
    assert sorted(per_rep) == [0, 1] and sum(per_rep.values()) == 5
