"""Request-scoped serving observability: trace sampling composed with
the front door, per-request latency attribution summing to the
measured wall, SLO attainment + attribution in the stats surface, the
flight-recorder query path, and the Prometheus exposition of the
serving counters."""

import json
import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.obs import REGISTRY, tracer
from defer_tpu.obs.attrib import attribute_request, attribute_sampled
from defer_tpu.runtime.node import ChainDispatcher, StageNode
from defer_tpu.serve import ServeClient, TenantConfig
from defer_tpu.serve.client import fetch_events, fetch_stats
from defer_tpu.serve.frontdoor import ChainBackend, ServeFrontDoor

IN_SHAPE = (32, 32, 3)


@pytest.fixture(scope="module")
def traced_door():
    """A 2-stage delay-bound chain behind a front door with tracing on
    and every request sampled (trace_sample_every=1) — the vehicle for
    the attribution/trace assertions.  In-process thread nodes share
    one tracer, so all spans land on one exact clock."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=2)
    tr = tracer()
    tr.enabled = True
    tr.process = "serve"
    tr.start_trace()
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in stages]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    # decode-side sleep on the stage0->stage1 hop: a real (non-CPU)
    # transport.hop1 cost the attribution must find
    disp.deploy(stages, params, addrs, batch=2,
                codecs=["dsleep5+raw", "raw"])
    door = ServeFrontDoor(
        backend=ChainBackend(disp, 2, IN_SHAPE, trace_sample_every=1),
        tenants=[TenantConfig("obs_gold", deadline_ms=5000.0)]).start()
    yield door, addrs
    door.stop()
    for t in threads:
        t.join(timeout=30)
    tr.enabled = False
    tr.clear()


def _stream(door, tenant, n=3, deadline_ms=5000.0):
    host, port = door.address
    rng = np.random.default_rng(7)
    data = [rng.standard_normal(IN_SHAPE).astype(np.float32)
            for _ in range(n)]
    outs = ServeClient(host, port, tenant,
                       deadline_ms=deadline_ms).stream(data)
    assert all(o is not None and o[0] == "ok" for o in outs), outs
    return outs


def _tenant_requests(spans, tenant):
    return sorted({int(s["args"]["rid"]) for s in spans
                   if s["name"] == "serve.request"
                   and s["args"].get("tenant") == tenant})


def test_sampled_request_trace_is_complete_and_ordered(traced_door):
    """The regression bar: a sampled served request's trace contains
    admission + gather + EVERY stage + result-edge spans, and their
    completion points are monotone on the timeline (no negative
    inter-span gaps)."""
    door, _ = traced_door
    _stream(door, "obs_gold", n=3)
    spans = tracer().spans
    rids = _tenant_requests(spans, "obs_gold")
    assert len(rids) == 3, f"every request must be sampled: {rids}"
    for rid in rids:
        mine = {s["name"]: s for s in spans
                if (s["args"] or {}).get("rid") == rid}
        root = mine["serve.request"]
        seq = root["args"]["seq"]
        frame = {s["name"]: s for s in spans
                 if (s["args"] or {}).get("seq") == seq}
        chain = [mine["serve.admission_wait"], frame["serve.gather"],
                 frame["stage0.infer"], frame["stage1.infer"],
                 mine["serve.deliver"]]
        # spans are integer-microsecond quantized (ts/dur truncate
        # independently), so boundaries shared by two spans can
        # disagree by a few us — that slack, never more
        slack = 3
        ends = [s["ts_us"] + s["dur_us"] for s in chain]
        for a, b in zip(ends, ends[1:]):
            assert b >= a - slack, (
                f"rid {rid}: negative inter-span gap in "
                f"{[s['name'] for s in chain]} -> {ends}")
        # the whole waterfall sits inside the request's root envelope
        assert root["ts_us"] <= chain[0]["ts_us"] + slack
        assert ends[-1] <= root["ts_us"] + root["dur_us"] + slack


def test_attribution_buckets_sum_to_measured_wall(traced_door):
    """Acceptance bar: the folded budget buckets (admission + gather +
    per-stage + per-hop transport + result edge) sum to within 10% of
    the request's measured end-to-end latency, and the delay-bound hop
    dominates where physics says it must."""
    door, _ = traced_door
    _stream(door, "obs_attr", n=4)
    spans = tracer().spans
    reps = [r for r in attribute_sampled(
        spans, hop_tiers=["tcp", "tcp", "tcp"])
        if r.tenant == "obs_attr"]
    assert len(reps) == 4
    for rep in reps:
        assert rep.ok(0.10), rep.to_json()
        for want in ("admission", "gather", "transport.hop0", "stage0",
                     "transport.hop1", "stage1", "host_sync",
                     "transport.result", "result_edge"):
            assert want in rep.buckets, rep.buckets
        assert rep.tiers["transport.hop0"] == "tcp"
        # the dsleep5 decode rides the stage0->stage1 hop: that
        # transport bucket must carry (at least) the injected 5 ms
        assert rep.buckets["transport.hop1"] >= 4.0, rep.to_json()
        assert rep.wall_ms >= 5.0
    one = attribute_request(spans, reps[0].rid)
    assert one is not None and one.rid == reps[0].rid
    assert attribute_request(spans, 10**9) is None


def test_stats_carry_slo_attainment_and_attribution(traced_door):
    """monitor --serve surface: per-tenant SLO attainment next to the
    queue-delay percentiles, door attribution buckets in the reply."""
    door, _ = traced_door
    _stream(door, "obs_slo", n=2, deadline_ms=60_000.0)
    host, port = door.address
    doc = fetch_stats(host, port)
    row = doc["tenants"]["obs_slo"]
    assert row["slo_attainment"] == 1.0
    assert row["slo_measured"] == 2
    buckets = doc["attribution"]["obs_slo"]
    assert buckets["e2e"]["count"] == 2
    for k in ("admission", "gather", "chain", "result_edge"):
        assert buckets[k]["count"] == 2
    # the buckets tile the e2e wall: p50s sum close to the e2e p50
    total = sum(buckets[k]["p50"]
                for k in ("admission", "gather", "chain", "result_edge"))
    assert total == pytest.approx(buckets["e2e"]["p50"], rel=0.35)
    assert "events_dropped" in doc
    # a tenant without a deadline is never scored
    _stream(door, "obs_noslo", n=1, deadline_ms=None)
    row2 = fetch_stats(host, port)["tenants"]["obs_noslo"]
    assert row2["slo_attainment"] is None


def test_events_since_queries_node_and_door(traced_door):
    """The flight-recorder query path: a stage node's control socket
    answers {"cmd": "events_since"}, and the front door answers the
    observer twin — both carrying the serving run's structured facts."""
    from defer_tpu.transport.framed import (K_CTRL, connect_retry,
                                            recv_expect, send_ctrl,
                                            send_end)
    door, addrs = traced_door
    host, _, port = addrs[0].rpartition(":")
    s = connect_retry(host, int(port), 30.0)
    try:
        send_ctrl(s, {"cmd": "events_since", "cursor": 0})
        reply = recv_expect(s, K_CTRL)
        send_end(s)
    finally:
        s.close()
    assert reply["cmd"] == "events_reply"
    assert isinstance(reply["dropped"], int)  # ring-loss visibility
    kinds = {e["kind"] for e in reply["events"]}
    assert "stream_begin" in kinds and "admit" in kinds
    # in-process chain shares one recorder: stage labels prove the
    # node-side emission sites fired
    hops = {e["data"].get("hop") for e in reply["events"]
            if e["kind"] == "stream_begin"}
    assert "stage0" in hops and "stage1" in hops
    dh, dp = door.address
    rep = fetch_events(dh, dp, cursor=0)
    assert {e["kind"] for e in rep["events"]} >= {"client_open",
                                                 "client_close"}
    # incremental contract: the returned cursor yields nothing new
    again = fetch_events(dh, dp, cursor=rep["cursor"])
    assert again["events"] == []


def test_monitor_cli_renders_events_and_slo(traced_door, capsys):
    """monitor --serve --events --json: the line carries the merged
    event log and the per-tenant SLO/attribution columns."""
    from defer_tpu import cli
    door, _ = traced_door
    host, port = door.address
    cli.main(["monitor", "--serve", f"{host}:{port}", "--events",
              "--iterations", "1", "--interval-ms", "50", "--json"])
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    doc = next(json.loads(ln) for ln in lines if '"serve"' in ln)
    kinds = {e["kind"] for e in doc["events"]}
    assert "client_open" in kinds
    assert doc["events_dropped"] == 0
    assert "slo_attainment" in doc["serve"]["tenants"]["obs_gold"]
    assert "attribution" in doc["serve"]


def test_prom_exposition_carries_serving_metrics(traced_door):
    """serve --prom-port satellite: the registry exposition the scrape
    endpoint serves includes the front door's counters and per-tenant
    histograms (sanitized names, quantile lines)."""
    door, _ = traced_door
    text = REGISTRY.exposition()
    assert "serve_admitted" in text and "serve_shed" in text
    assert "serve_tenant_obs_gold_admitted" in text
    assert 'serve_tenant_obs_gold_queue_delay_s{quantile="0.99"}' in text
    assert "events_dropped" in text


def test_trace_compose_still_rejects_fan_restamping():
    """The one place request-scoped tracing is rejected loudly: a
    replicated first/last stage re-stamps the wire seq space, so
    request frames (sampled or not) refuse to ride it."""
    disp = ChainDispatcher.__new__(ChainDispatcher)
    disp.result_fan_in = 2
    disp._send_sock = object()
    disp._tx_chan = object()
    with pytest.raises(ValueError, match="non-replicated"):
        disp.send_request_frame(np.zeros((1, 2)), seq=0)
