"""Ring attention == full attention, sharded over the seq mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from defer_tpu.parallel.ring_attention import (full_attention,
                                               sequence_parallel_attention)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


@pytest.mark.parametrize("n,causal", [(2, False), (4, False), (8, False),
                                      (4, True), (8, True)])
def test_ring_matches_full(n, causal):
    b, h, t, d = 2, 3, 8 * n, 16
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (b, h, t, d))
    k = jax.random.normal(ks[1], (b, h, t, d))
    v = jax.random.normal(ks[2], (b, h, t, d))
    ref = full_attention(q, k, v, causal=causal)
    out = sequence_parallel_attention(q, k, v, _mesh(n), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_long_context_memory_shape():
    """Per-device score matrix is Tl x Tl, not T x T: the point of SP."""
    n = 8
    b, h, t, d = 1, 2, 16 * n, 8
    mesh = _mesh(n)
    q = jnp.ones((b, h, t, d))
    out = sequence_parallel_attention(q, q, q, mesh)
    assert out.shape == (b, h, t, d)
    # uniform inputs -> attention output equals v everywhere
    np.testing.assert_allclose(np.asarray(out), np.ones((b, h, t, d)),
                               rtol=1e-5)


def test_causal_first_token_attends_self_only():
    n = 4
    b, h, t, d = 1, 1, 4 * n, 8
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (b, h, t, d))
    k = jax.random.normal(ks[1], (b, h, t, d))
    v = jax.random.normal(ks[2], (b, h, t, d))
    out = sequence_parallel_attention(q, k, v, _mesh(n), causal=True)
    np.testing.assert_allclose(np.asarray(out[0, 0, 0]),
                               np.asarray(v[0, 0, 0]), rtol=1e-5, atol=1e-5)
