"""Serving front door: admission (WFQ/priorities/shedding), continuous
batching (chain + decode), request-scoped demux, and the edge cases the
admission layer must survive (greedy neighbors, shed-then-retry,
mid-decode disconnects).
"""

import json
import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.models.gpt import gpt_tiny
from defer_tpu.plan import StageCostModel, max_batch_within_budget, \
    stage_ms_at_batch
from defer_tpu.runtime.node import ChainDispatcher, StageNode
from defer_tpu.serve import (AdmissionController, ContinuousBatchEngine,
                             DecodeRequest, ServeClient, TenantConfig,
                             WeightedFairQueue, poisson_trace)
from defer_tpu.serve.client import fetch_stats
from defer_tpu.serve.frontdoor import ChainBackend, ServeFrontDoor


# ---------------------------------------------------------------------------
# arrival traces
# ---------------------------------------------------------------------------

def test_poisson_trace_deterministic_and_bursty():
    a = poisson_trace(50.0, 4.0, seed=7, bursts=[(1.0, 2.0, 3.0)])
    b = poisson_trace(50.0, 4.0, seed=7, bursts=[(1.0, 2.0, 3.0)])
    assert a == b, "same seed must reproduce the same trace"
    assert a == sorted(a) and all(0 <= t < 4.0 for t in a)
    c = poisson_trace(50.0, 4.0, seed=8, bursts=[(1.0, 2.0, 3.0)])
    assert a != c
    # the burst window must actually run ~3x hot vs the steady phases
    in_burst = sum(1 for t in a if 1.0 <= t < 2.0)
    steady = sum(1 for t in a if t < 1.0 or t >= 2.0) / 3.0
    assert in_burst > 1.8 * steady, (in_burst, steady)


def test_poisson_trace_validates_phases():
    with pytest.raises(ValueError):
        poisson_trace(10, 1, bursts=[(0.5, 0.2, 2.0)])
    assert poisson_trace(0, 5) == []


# ---------------------------------------------------------------------------
# weighted-fair queuing
# ---------------------------------------------------------------------------

def test_wfq_fairness_bound_under_greedy_neighbor():
    """A greedy tenant pre-loading its whole queue cannot starve a
    steady neighbor: over any backlogged prefix the served counts track
    the weight ratio to within one unit per tenant (the SFQ bound)."""
    q = WeightedFairQueue()
    q.configure(TenantConfig("greedy", weight=1.0))
    q.configure(TenantConfig("steady", weight=1.0))
    for i in range(60):
        q.push("greedy", f"g{i}")  # the flood lands first
    for i in range(10):
        q.push("steady", f"s{i}")
    served = [q.pop()[0] for _ in range(70)]
    # while both are backlogged (first 20 pops), shares stay within the
    # fairness bound despite greedy's 60-deep head start
    for k in range(1, 21):
        g = served[:k].count("greedy")
        s = served[:k].count("steady")
        assert abs(g - s) <= 1, (k, g, s)


def test_wfq_weights_shape_the_share():
    q = WeightedFairQueue()
    q.configure(TenantConfig("heavy", weight=3.0))
    q.configure(TenantConfig("light", weight=1.0))
    for i in range(80):
        q.push("heavy", i)
        q.push("light", i)
    first = [q.pop()[0] for _ in range(40)]
    h, light = first.count("heavy"), first.count("light")
    # 3:1 weights -> ~3:1 service while both are backlogged
    assert 2.0 <= h / max(light, 1) <= 4.0, (h, light)


def test_wfq_strict_priority_preempts():
    q = WeightedFairQueue()
    q.configure(TenantConfig("bulk", weight=5.0, priority=0))
    q.configure(TenantConfig("interactive", weight=1.0, priority=1))
    for i in range(5):
        q.push("bulk", i)
    q.push("interactive", "now")
    assert q.pop()[0] == "interactive", \
        "higher priority level must drain first regardless of weights"
    assert q.pop()[0] == "bulk"


def test_wfq_reconfigure_moves_priority_level():
    """Review regression: re-configuring a tenant's priority must MOVE
    its queue (items included) to the new level, and a later drop must
    not corrupt the size accounting."""
    q = WeightedFairQueue()
    q.configure(TenantConfig("a", priority=0))
    q.configure(TenantConfig("b", priority=0))
    for i in range(3):
        q.push("a", i)
    q.push("b", "x")
    q.configure(TenantConfig("a", priority=1))  # promote mid-backlog
    assert q.pop()[0] == "a", "promoted tenant must drain first"
    assert q.qsize() == 3
    q.push("a", 99)  # new pushes land in the NEW level
    assert q.pop()[0] == "a"
    assert q.drop_tenant("a") == 2  # items 2 and 99 discarded
    assert q.qsize("a") == 0 and q.qsize() == 1  # b's unit intact
    assert q.pop() == ("b", "x") and q.qsize() == 0


def test_wfq_blocking_pop_and_drop_tenant():
    q = WeightedFairQueue()
    q.configure(TenantConfig("a"))
    assert q.pop(timeout=0.0) is None
    t = threading.Timer(0.05, lambda: q.push("a", 1))
    t.start()
    assert q.pop(timeout=2.0) == ("a", 1)
    q.push("a", 2)
    q.push("a", 3)
    assert q.drop_tenant("a") == 2 and q.qsize() == 0
    q.push("a", 4)  # still configured after the drop
    assert q.pop() == ("a", 4)


# ---------------------------------------------------------------------------
# admission / shedding
# ---------------------------------------------------------------------------

def test_admission_shed_then_retry_lifecycle():
    """The satellite lifecycle: admit while the prediction fits, shed
    with a retry hint when the backlog blows the deadline, admit again
    once completions drain the backlog."""
    ctl = AdmissionController(service_s=lambda: 0.1)
    ctl.configure(TenantConfig("t", deadline_ms=250.0))
    d1 = ctl.admit("t", "u1")
    d2 = ctl.admit("t", "u2")
    assert d1.admitted and d2.admitted
    d3 = ctl.admit("t", "u3")  # predicted (2+1)*0.1 = 0.3 > 0.25
    assert not d3.admitted and d3.reason == "deadline"
    assert d3.retry_after_s > 0 and d3.predicted_s > 0.25
    ctl.complete("t", queued_at=time.monotonic())
    d4 = ctl.admit("t", "u3-retry")  # backlog drained below the SLO
    assert d4.admitted
    stats = ctl.stats()
    assert stats["tenants"]["t"]["admitted"] == 3
    assert stats["tenants"]["t"]["shed"] == 1
    assert stats["tenants"]["t"]["completed"] == 1


def test_admission_backlog_cap_sheds_without_deadline():
    ctl = AdmissionController(service_s=lambda: 0.0)
    ctl.configure(TenantConfig("t", max_queued=2))
    assert ctl.admit("t", 1).admitted and ctl.admit("t", 2).admitted
    d = ctl.admit("t", 3)
    assert not d.admitted and d.reason == "backlog"


def test_admission_ewma_and_per_tenant_isolation():
    ctl = AdmissionController()
    ctl.configure(TenantConfig("slo", deadline_ms=50.0))
    ctl.configure(TenantConfig("besteffort"))  # no deadline: never SLO-shed
    ctl.observe_service(0.2)
    assert ctl.service_estimate_s() == pytest.approx(0.2)
    assert not ctl.admit("slo", 1).admitted      # 0.2s >> 50ms
    assert ctl.admit("besteffort", 1).admitted   # deadline-free rides on


# ---------------------------------------------------------------------------
# latency-budget queries (plan/)
# ---------------------------------------------------------------------------

def test_latency_budget_width_query():
    g = resnet_tiny()
    stages = partition(g, num_stages=3)
    cuts = [s.output_name for s in stages[:-1]]
    cm = StageCostModel(g, batch=1)
    ms1 = max(stage_ms_at_batch(g, cuts, cm, 1))
    ms8 = max(stage_ms_at_batch(g, cuts, cm, 8))
    assert ms8 > ms1 > 0, "stage time must grow with batch"
    assert max_batch_within_budget(g, cuts, cm, ms1 * 0.5) == 1, \
        "a budget below the single-sample cost degrades to width 1"
    w = max_batch_within_budget(g, cuts, cm, ms8, cap=64)
    assert 8 <= w <= 64
    assert max(stage_ms_at_batch(g, cuts, cm, w)) <= ms8 + 1e-9
    big = max_batch_within_budget(g, cuts, cm, 1e9, cap=16)
    assert big == 16, "an unbounded budget saturates the cap"


def test_latency_budget_scales_measured_costs():
    g = resnet_tiny()
    stages = partition(g, num_stages=2)
    cuts = [s.output_name for s in stages[:-1]]
    node_costs = {n: 1e-4 for n in g.topo_order}
    cm = StageCostModel(g, batch=1, node_costs=node_costs)
    ms2 = stage_ms_at_batch(g, cuts, cm, 2)
    ms1 = stage_ms_at_batch(g, cuts, cm, 1)
    # measured costs scale linearly with the candidate batch
    assert max(ms2) == pytest.approx(2 * max(ms1), rel=0.2)


# ---------------------------------------------------------------------------
# continuous-batching decode engine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt_setup():
    g = gpt_tiny()
    return g, g.init(jax.random.key(0))


def _prompts(n, rng):
    return [rng.integers(0, 97, (int(p),)).astype(np.int32)
            for p in rng.integers(2, 6, n)]


def test_engine_byte_identity_solo_vs_continuous(gpt_setup):
    """The correctness bar: per-request outputs byte-identical to the
    request run alone, with requests JOINING AT DIFFERENT STEPS (true
    continuous batching, not lockstep), greedy and sampled rows mixed."""
    g, params = gpt_setup
    rng = np.random.default_rng(3)
    prompts = _prompts(3, rng)

    def make_reqs():
        return [DecodeRequest(prompt=p, max_new_tokens=5, request_id=i,
                              seed=100 + i,
                              temperature=0.8 if i == 1 else 0.0)
                for i, p in enumerate(prompts)]

    solo = {}
    for req in make_reqs():
        eng = ContinuousBatchEngine(g, params, num_stages=2, width=3)
        solo[req.request_id] = eng.run_all([req])[req.request_id]

    eng = ContinuousBatchEngine(g, params, num_stages=2, width=3)

    def stagger(e, queue):
        while queue and e.free_slots() \
                and e.steps >= 3 * queue[0].request_id:
            e.join(queue.pop(0))

    batched = eng.run_all(make_reqs(), joiner=stagger)
    for rid, ids in solo.items():
        np.testing.assert_array_equal(batched[rid], ids)


def test_engine_cancel_reclaims_slot_others_unaffected(gpt_setup):
    """A client disconnecting mid-decode: its slot is reclaimed (a new
    request joins into it) and the surviving request's output is
    byte-identical to an undisturbed run."""
    g, params = gpt_setup
    rng = np.random.default_rng(4)
    p_victim, p_survivor, p_late = _prompts(3, rng)
    solo_eng = ContinuousBatchEngine(g, params, num_stages=2, width=2)
    survivor_solo = solo_eng.run_all(
        [DecodeRequest(prompt=p_survivor, max_new_tokens=6,
                       request_id=1)])[1]

    eng = ContinuousBatchEngine(g, params, num_stages=2, width=2)
    victim = DecodeRequest(prompt=p_victim, max_new_tokens=10,
                           request_id=0)
    survivor = DecodeRequest(prompt=p_survivor, max_new_tokens=6,
                             request_id=1)
    seen = []
    victim.on_done = seen.append
    assert eng.join(victim) and eng.join(survivor)
    assert eng.free_slots() == 0
    for _ in range(3):
        eng.step()
    assert eng.cancel(victim)
    assert seen == [None], "cancellation must signal on_done(None)"
    assert eng.free_slots() == 1, "the KV slot must be reclaimed"
    late = DecodeRequest(prompt=p_late, max_new_tokens=2, request_id=2)
    assert eng.join(late), "a new request must fit the reclaimed slot"
    out = {}
    while eng.active():
        for req, ids in eng.step():
            out[req.request_id] = ids
    np.testing.assert_array_equal(out[1], survivor_solo)
    assert 2 in out and eng.free_slots() == 2


def test_engine_validates_requests(gpt_setup):
    g, params = gpt_setup
    eng = ContinuousBatchEngine(g, params, num_stages=2, width=1)
    with pytest.raises(ValueError, match="max_len"):
        eng.join(DecodeRequest(prompt=np.arange(10), max_new_tokens=99))
    with pytest.raises(ValueError, match="at least one token"):
        DecodeRequest(prompt=np.zeros((0,)), max_new_tokens=1)
    assert eng.join(DecodeRequest(prompt=np.arange(3), max_new_tokens=1))
    assert not eng.join(
        DecodeRequest(prompt=np.arange(3), max_new_tokens=1)), \
        "a full batch refuses joins until a slot frees"


# ---------------------------------------------------------------------------
# request-scoped chain streaming (req_meta + seq namespace)
# ---------------------------------------------------------------------------

def _boot_chain(stages, params, batch, *, codecs=None):
    nodes = [StageNode(None, "127.0.0.1:0", None) for _ in stages]
    addrs = [f"127.0.0.1:{n.address[1]}" for n in nodes]
    threads = [threading.Thread(target=n.serve, daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw")
    disp.deploy(stages, params, addrs, batch=batch, codecs=codecs)
    return disp, threads


@pytest.fixture(scope="module")
def resnet_setup():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def test_req_meta_cascades_ahead_of_its_frame(resnet_setup):
    """The node-side contract: a req_meta K_CTRL cascades through every
    stage and arrives on the result hop BEFORE the frame it describes
    (it may overtake earlier frames — the demux joins by seq), with the
    v2 seq stamp relayed end to end and both kinds in send order."""
    g, params = resnet_setup
    stages = partition(g, num_stages=2)
    disp, threads = _boot_chain(stages, params, 2)
    try:
        rng = np.random.default_rng(0)
        xs = [rng.standard_normal((2, 32, 32, 3)).astype(np.float32)
              for _ in range(3)]
        for i, x in enumerate(xs):
            disp.send_request_frame(x, seq=1000 + i,
                                    meta={"slots": [["t", i, i, 0]]})
        got = [disp.recv_result(timeout_s=60.0) for _ in range(6)]
        metas = [v for k, v in got if k == "meta"]
        tensors = [v for k, v in got if k == "tensor"]
        assert len(metas) == 3 and len(tensors) == 3
        assert [m["seq"] for m in metas] == [1000, 1001, 1002]
        assert [m["slots"] for m in metas] == [[["t", i, i, 0]]
                                               for i in range(3)]
        assert [s for s, _ in tensors] == [1000, 1001, 1002]
        for i in range(3):
            at_meta = next(j for j, (k, v) in enumerate(got)
                           if k == "meta" and v["seq"] == 1000 + i)
            at_tensor = next(j for j, (k, v) in enumerate(got)
                             if k == "tensor" and v[0] == 1000 + i)
            assert at_meta < at_tensor, \
                f"meta for frame {i} arrived after its tensor"
    finally:
        disp.close()
        for t in threads:
            t.join(timeout=30)


def test_request_frames_reject_replicated_chains(resnet_setup):
    disp = ChainDispatcher.__new__(ChainDispatcher)
    disp.result_fan_in = 2
    disp._send_sock = object()  # pretend connected
    disp._tx_chan = object()
    with pytest.raises(ValueError, match="non-replicated"):
        disp.send_request_frame(np.zeros((1, 2)), seq=0)


# ---------------------------------------------------------------------------
# the front door end to end (tensor mode over an in-process chain)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tensor_door(resnet_setup):
    g, params = resnet_setup
    stages = partition(g, num_stages=2)
    disp, threads = _boot_chain(stages, params, 4)
    door = ServeFrontDoor(
        backend=ChainBackend(disp, 4, (32, 32, 3))).start()
    yield g, params, door
    door.stop()
    for t in threads:
        t.join(timeout=30)


def test_frontdoor_multitenant_byte_identity(tensor_door):
    """Three concurrent tenant streams over ONE deployed chain: every
    per-request output byte-identical to the request run alone through
    the same serving path (the acceptance bar)."""
    g, params, door = tensor_door
    host, port = door.address
    rng = np.random.default_rng(11)
    data = {t: [rng.standard_normal((32, 32, 3)).astype(np.float32)
                for _ in range(3)] for t in ("alpha", "beta", "gamma")}
    solo = {t: ServeClient(host, port, t + "_solo").stream(data[t])
            for t in data}
    outs = {}

    def run_tenant(t):
        outs[t] = ServeClient(host, port, t).stream(data[t])

    ths = [threading.Thread(target=run_tenant, args=(t,)) for t in data]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=120)
    door.healthcheck()
    for t in data:
        for i in range(len(data[t])):
            assert outs[t][i][0] == "ok" and solo[t][i][0] == "ok"
            np.testing.assert_array_equal(outs[t][i][1], solo[t][i][1])
    doc = fetch_stats(host, port)
    assert doc["mode"] == "tensor" and doc["width"] == 4
    assert doc["tenants"]["alpha"]["completed"] == 3


def test_frontdoor_shed_reply_and_retry(tensor_door):
    """Overload a deadline-bound tenant: the client receives shed
    control frames (with prediction + retry hint) instead of late
    results, and a retry after the backlog drains is served."""
    g, params, door = tensor_door
    host, port = door.address
    # pin the service estimate high so the SLO math sheds immediately
    # and deterministically (the live EWMA would need real overload)
    door.admission._service_s = lambda: 0.5
    try:
        c = ServeClient(host, port, "slo_tenant", deadline_ms=600.0)
        x = np.zeros((32, 32, 3), np.float32)
        for _ in range(4):
            c.submit(x)
        # give the first admissions a moment to resolve, then retry
        time.sleep(1.0)
        retry_seq = c.submit(x)
        results = c.finish()
        outcomes = [results[q][0] for q in sorted(results)]
        assert "shed" in outcomes, outcomes
        shed = next(v for v in results.values() if v[0] == "shed")
        assert shed[1]["reason"] == "deadline"
        assert shed[1]["retry_after_ms"] > 0
        assert shed[1]["predicted_ms"] > 600.0
        assert results[retry_seq][0] == "ok", \
            "a retry after the backlog drained must be admitted"
    finally:
        door.admission._service_s = None


# ---------------------------------------------------------------------------
# the front door end to end (decode mode) + disconnect mid-decode
# ---------------------------------------------------------------------------

@pytest.fixture()
def decode_door():
    # a longer positional table than gpt_tiny's 16 so the "victim" can
    # run a generation long enough to be caught mid-decode
    g = gpt_tiny(seq_len=48)
    params = g.init(jax.random.key(0))
    engine = ContinuousBatchEngine(g, params, num_stages=2, width=3)
    door = ServeFrontDoor(engine=engine,
                          decode_defaults={"max_new_tokens": 4}).start()
    yield g, params, door
    door.stop()


def test_frontdoor_decode_roundtrip_and_disconnect(decode_door):
    """Decode mode: concurrent tenants' generations are byte-identical
    to solo runs; a client disconnecting mid-decode frees its KV slot
    and leaves the other tenant's output untouched."""
    g, params, door = decode_door
    host, port = door.address
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, 97, (4,)).astype(np.int32)
               for _ in range(2)]
    solo = ServeClient(host, port, "ref",
                       max_new_tokens=4).stream([prompts[0]])
    assert solo[0][0] == "ok"

    # victim starts a long generation then disconnects without END
    victim = ServeClient(host, port, "victim", max_new_tokens=40)
    victim.submit(prompts[1])
    deadline = time.monotonic() + 30
    while door.engine.active() == 0:
        assert time.monotonic() < deadline, "victim never joined"
        time.sleep(0.01)
    victim.abort()

    steady = ServeClient(host, port, "steady", max_new_tokens=4)
    out = steady.stream([prompts[0]])
    assert out[0][0] == "ok"
    np.testing.assert_array_equal(out[0][1], solo[0][1])

    deadline = time.monotonic() + 30
    while door.engine.free_slots() != door.engine.width:
        assert time.monotonic() < deadline, \
            "the disconnected client's KV slot was never reclaimed"
        time.sleep(0.05)
    # decode attribution: the engine's residency lands in the CHAIN
    # bucket (queue wait in admission), not all-in-admission
    buckets = fetch_stats(host, port)["attribution"]["steady"]
    assert buckets["e2e"]["count"] == 1
    assert buckets["chain"]["p50"] > 0
    assert buckets["chain"]["p50"] > buckets["admission"]["p50"]
    door.healthcheck()


# ---------------------------------------------------------------------------
# the CLI surface (in-process: serve + serve-client + monitor --serve)
# ---------------------------------------------------------------------------

def test_cli_serve_serve_client_and_monitor(capsys):
    from defer_tpu import cli
    from defer_tpu.runtime.node import _free_ports

    port = _free_ports(1)[0]
    addr = f"127.0.0.1:{port}"
    t = threading.Thread(
        target=cli.main,
        args=(["serve", "--model", "resnet_tiny", "--stages", "2",
               "--width", "2", "--listen", addr, "--seconds", "6",
               "--tenant", "gold=2.0:1:5000"],),
        daemon=True)
    t.start()
    # the load-generating client CLI against the booting door
    cli.main(["serve-client", "--connect", addr, "--tenant", "gold",
              "--rate", "30", "--seconds", "1", "--seed", "3",
              "--burst", "0.2:0.6:2.0"])
    # the monitor's per-tenant serve columns
    cli.main(["monitor", "--serve", addr, "--iterations", "1",
              "--interval-ms", "50", "--json"])
    # the serve thread must finish INSIDE this test: a stray print
    # after --seconds elapse would land in some other test's capture
    t.join(timeout=60)
    assert not t.is_alive()
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    gen = next(json.loads(ln) for ln in lines
               if "latency_p99_ms" in ln)
    assert gen["tenant"] == "gold" and gen["completed"] >= 1
    assert gen["shed"] == 0, "a 5s SLO at 30 Hz must not shed"
    mon = next(json.loads(ln) for ln in lines if '"serve"' in ln)
    assert mon["serve"]["mode"] == "tensor"
    assert mon["serve"]["tenants"]["gold"]["weight"] == 2.0
    assert mon["serve"]["tenants"]["gold"]["completed"] \
        == gen["completed"]
