"""Shared-memory transport tier (docs/TRANSPORT.md): ring semantics,
same-host negotiation + per-hop fallback labeling, the planner's shm
pseudo-codec, segment lifecycle, and the real-OS-process end-to-end
negotiation — the pytest half of ``scripts/shm_smoke.py``.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import partition
from defer_tpu.models import resnet_tiny
from defer_tpu.obs import REGISTRY
from defer_tpu.runtime.node import ChainDispatcher, StageNode
from defer_tpu.transport.channel import AsyncReceiver, ChannelError
from defer_tpu.transport.framed import (K_CTRL, K_END, K_TENSOR,
                                        K_TENSOR_SEQ, PROTOCOL_VERSION,
                                        recv_frame, send_ctrl)
from defer_tpu.transport.shm import (SEG_PREFIX, ShmRing, _boot_id,
                                     answer_tier_probe, grant_shm,
                                     offer_shm, sweep_orphan_segments)

CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}


def _counter(name: str) -> float:
    return REGISTRY.counter(name).value


def _hist_count(name: str) -> int:
    return int(REGISTRY.histogram(name).summary().get("count", 0))


def _segments() -> set:
    try:
        return {n for n in os.listdir("/dev/shm")
                if n.startswith(SEG_PREFIX)}
    except OSError:
        return set()


def _negotiate(*, depth: int = 4, slot_bytes: int = 256,
               accept: bool = True):
    """socketpair negotiation: returns (sock_a, sock_b, sender, rx)."""
    a, b = socket.socketpair()
    inner = AsyncReceiver(b, depth=8)
    state = {}

    def peer():
        kind, msg = inner.get(5.0)
        assert kind == K_CTRL and msg["cmd"] == "tier_probe"
        state["tier"], state["chan"] = answer_tier_probe(
            b, msg, accept=accept, inner=inner)

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    tier, tx = offer_shm(a, depth=depth, slot_bytes=slot_bytes)
    t.join(5.0)
    return a, b, tier, tx, state.get("chan")


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_roundtrip_order_seq_ctrl_end():
    a, b, tier, tx, rx = _negotiate()
    assert tier == "shm" and tx is not None and rx is not None
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    tx.send_ctrl({"cmd": "trace", "trace_id": "t"})
    tx.send(arr)
    tx.send(arr * 2, seq=7)
    assert rx.get(5.0) == (K_CTRL, {"cmd": "trace", "trace_id": "t"})
    kind, v = rx.get(5.0)
    assert kind == K_TENSOR
    np.testing.assert_array_equal(v, arr)
    assert v.flags.owndata or v.base is None or True  # owned copy
    kind, (seq, v) = rx.get(5.0)
    assert kind == K_TENSOR_SEQ and seq == 7
    np.testing.assert_array_equal(v, arr * 2)
    tx.close(timeout=5.0)
    assert rx.get(5.0) == (K_END, None)
    rx.release_gauge()
    a.close()
    b.close()


def test_ring_result_survives_slot_reuse():
    """The materialized array must be exclusively owned: a result held
    by the caller across ``depth`` further frames (the dispatcher's
    outs list) must not be silently overwritten by slot recycling."""
    a, b, tier, tx, rx = _negotiate(depth=2)
    first = np.arange(8, dtype=np.float32)
    tx.send(first)
    kind, kept = rx.get(5.0)
    for i in range(6):  # recycle every slot several times over
        tx.send(np.full(8, 100.0 + i, np.float32))
        rx.get(5.0)
    np.testing.assert_array_equal(kept, first)
    tx.close(timeout=5.0)
    rx.release_gauge()
    a.close()
    b.close()


def test_ring_backpressure_is_bounded():
    a, b, tier, tx, rx = _negotiate(depth=2)
    sent = []

    def produce():
        for i in range(6):
            tx.send(np.full(4, i, np.float32))
            sent.append(i)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    t.join(0.5)
    assert t.is_alive() and len(sent) <= 2  # parked on the full ring
    got = [int(rx.get(5.0)[1][0]) for _ in range(6)]
    t.join(5.0)
    assert not t.is_alive()
    assert got == list(range(6))  # in order, nothing dropped
    tx.close(timeout=5.0)
    rx.release_gauge()
    a.close()
    b.close()


def test_ring_grows_past_slot_capacity():
    """A frame fatter than the slot swaps in a bigger segment (ordered
    ahead of the frames that need it) without leaking the old name."""
    a, b, tier, tx, rx = _negotiate(slot_bytes=128)
    before = _segments()
    small = np.arange(8, dtype=np.float32)
    big = np.arange(4096, dtype=np.float32)
    got = []

    def consume():
        for _ in range(3):
            got.append(rx.get(10.0)[1])

    # grow DRAINS the ring first (outstanding slots must be acked), so
    # the consumer runs concurrently — as it does in a live chain
    t = threading.Thread(target=consume, daemon=True)
    t.start()
    tx.send(small)
    tx.send(big)        # > 128 bytes: grow
    tx.send(big * 2)
    t.join(15.0)
    assert not t.is_alive()
    np.testing.assert_array_equal(got[0], small)
    np.testing.assert_array_equal(got[1], big)
    np.testing.assert_array_equal(got[2], big * 2)
    tx.close(timeout=5.0)
    rx.release_gauge()
    a.close()
    b.close()
    assert _segments() <= before  # grown ring reaped, old ring too


def test_receiver_gone_wakes_parked_producer():
    a, b, tier, tx, rx = _negotiate(depth=1)
    tx.send(np.zeros(4, np.float32))
    err = []

    def produce():
        try:
            tx.send(np.ones(4, np.float32))  # parks on the full ring
        except ChannelError as e:
            err.append(e)

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    t.join(0.3)
    assert t.is_alive()
    rx.release_gauge()  # the consumer's stream loop exited
    t.join(5.0)
    assert not t.is_alive() and err, "parked producer never woke"
    tx.detach()
    a.close()
    b.close()


def test_sender_death_fails_receiver_and_reaps_segment():
    a, b, tier, tx, rx = _negotiate()
    tx.send(np.zeros(4, np.float32))
    rx.get(5.0)
    seg = tx._ring.name
    a.close()  # sender process gone: doorbell EOF
    with pytest.raises((ConnectionError, OSError)):
        rx.get(5.0)
    assert not os.path.exists(os.path.join("/dev/shm", seg)), (
        "receiver teardown must reap a dead sender's segment name")
    b.close()


# ---------------------------------------------------------------------------
# negotiation: grant validation + fallback labeling
# ---------------------------------------------------------------------------

def _probe_msg() -> dict:
    ring = ShmRing(slots=2, slot_bytes=128)
    return {"cmd": "tier_probe", "want": "shm",
            "proto": PROTOCOL_VERSION, "boot_id": _boot_id(),
            "seg": ring.name, "slots": 2, "slot_bytes": ring.slot_bytes}


def test_grant_opens_offered_segment():
    msg = _probe_msg()
    seg = grant_shm(msg)
    assert seg is not None and seg.name == msg["seg"]
    seg.close()


def test_grant_rejects_version_mismatch():
    msg = _probe_msg()
    msg["proto"] = PROTOCOL_VERSION + 1
    assert grant_shm(msg) is None


def test_grant_rejects_boot_id_mismatch():
    msg = _probe_msg()
    msg["boot_id"] = "not-this-host"
    assert grant_shm(msg) is None


def test_grant_rejects_unresolvable_segment():
    msg = _probe_msg()
    msg["seg"] = SEG_PREFIX + "999999_deadbeefdead"
    assert grant_shm(msg) is None


def test_refusal_degrades_and_counts_per_hop():
    """A refused shm offer comes back ("tcp", None) with BOTH the
    process-global counter and the per-hop labeled twin bumped — the
    satellite contract: a degraded hop is attributable."""
    a, b = socket.socketpair()

    def peer():
        kind, msg = recv_frame(b)
        assert kind == K_CTRL and msg["cmd"] == "tier_probe"
        send_ctrl(b, {"cmd": "tier_reply", "tier": "tcp"})

    t = threading.Thread(target=peer, daemon=True)
    t.start()
    g0 = _counter("transport.tier_fallback")
    h0 = _counter("transport.tier_fallback.stage7")
    tier, tx = offer_shm(a, hop="stage7")
    t.join(5.0)
    assert (tier, tx) == ("tcp", None)
    assert _counter("transport.tier_fallback") == g0 + 1
    assert _counter("transport.tier_fallback.stage7") == h0 + 1
    a.close()
    b.close()


def test_answer_probe_refuses_when_not_accepting():
    a, b, tier, tx, rx = _negotiate(accept=False)
    assert (tier, tx, rx) == ("tcp", None, None)
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# in-process chains: byte identity, zero codec work, fallback surfacing
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def _run_chain_inproc(stages, params, xs, *, tier, accepts=None,
                      codecs=None):
    n = len(stages)
    nodes = [StageNode(None, "127.0.0.1:0", None, tier=tier,
                       tier_accept=True if accepts is None else accepts[i])
             for i in range(n)]
    addrs = [f"127.0.0.1:{nd.address[1]}" for nd in nodes]
    threads = [threading.Thread(target=nd.serve, daemon=True)
               for nd in nodes]
    for t in threads:
        t.start()
    disp = ChainDispatcher(addrs[0], codec="raw", tier=tier)
    try:
        disp.deploy(stages, params, addrs, batch=xs[0].shape[0],
                    codecs=codecs, tiers=[tier] * n)
        outs = disp.stream(xs)
        stats = disp.stats(addrs)
    finally:
        disp.close()
    for t in threads:
        t.join(timeout=60)
    return outs, stats


@pytest.fixture(scope="module")
def chain3(tiny):
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(5)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(5)]
    outs, stats = _run_chain_inproc(stages, params, xs, tier="tcp")
    return g, params, stages, xs, outs, stats


def test_shm_chain_byte_identical_zero_codec_work(chain3):
    """Every hop of a ``tier="shm"`` chain negotiates the ring, outputs
    are byte-identical to the all-TCP chain, ZERO ``codec.*`` samples
    are recorded, and no segment outlives the stream."""
    g, params, stages, xs, base, base_stats = chain3
    assert [s["tier"] for s in base_stats] == ["tcp"] * 3
    before = _segments()
    enc0, dec0 = _hist_count("codec.encode_s"), _hist_count("codec.decode_s")
    sf0 = _counter("transport.shm_frames")
    outs, stats = _run_chain_inproc(stages, params, xs, tier="shm")
    assert [s["tier"] for s in stats] == ["shm"] * 3
    assert [s["tier_in"] for s in stats] == ["shm"] * 3
    assert [s["tier_fallbacks"] for s in stats] == [0] * 3
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert _hist_count("codec.encode_s") == enc0, \
        "a shm hop recorded codec encode samples"
    assert _hist_count("codec.decode_s") == dec0, \
        "a shm hop recorded codec decode samples"
    # 4 hops (disp->s0->s1->s2->result) x len(xs) frames rode the rings
    assert _counter("transport.shm_frames") - sf0 == 4 * len(xs)
    assert _segments() <= before


def test_refused_shm_hop_degrades_with_labeled_fallback(chain3):
    """A hop whose peer refuses the offer degrades to tcp, the stream
    stays byte-identical, and the degraded hop is attributable: its
    stats row carries ``tier_fallbacks`` (the monitor renders it as
    ``tcp!``), unlike the never-offered hops around it."""
    g, params, stages, xs, base, _ = chain3
    before = _counter("transport.tier_fallback")
    outs, stats = _run_chain_inproc(stages, params, xs, tier="shm",
                                    accepts=[True, False, True])
    assert _counter("transport.tier_fallback") > before
    by_stage = {s["stage"]: s for s in stats}
    assert by_stage[0]["tier"] == "tcp"      # its offer was refused
    assert by_stage[0]["tier_fallbacks"] >= 1
    assert by_stage[1]["tier"] == "shm"      # stage 2 still granted
    assert by_stage[1]["tier_fallbacks"] == 0
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_monitor_renders_degraded_hop():
    """The TIER column distinguishes a DEGRADED hop (tcp!) from one
    that never offered anything better (tcp)."""
    import contextlib
    import io

    from defer_tpu.cli import _render_monitor
    row = {"stage": 0, "replica": None, "branch": None, "join": 0,
           "tier": "tcp", "tier_fallbacks": 1, "alive": True,
           "throughput_per_s": 1.0,
           "infer_ms": {"p50": 0.0, "p95": 0.0, "p99": 0.0},
           "rx_q": 0, "tx_q": 0, "rx_hi": 0, "tx_hi": 0, "inflight": 0,
           "rx_bytes_per_s": 0.0, "tx_bytes_per_s": 0.0,
           "processed": 1, "addr": "x"}
    plain = dict(row, tier_fallbacks=0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        _render_monitor([row, plain], None, [], {}, clear=False)
    lines = buf.getvalue().splitlines()
    assert any("tcp!" in ln for ln in lines[1:2]), lines
    assert "tcp!" not in lines[2]
    # the "!" marks a hop STILL riding tcp: a node that fell back once
    # but renegotiated shm on a later stream renders healthy, and the
    # untruncated 5-char "local" survives the degraded-mark suffixing
    healthy = dict(row, tier="shm")
    local = dict(row, tier="local", tier_fallbacks=0)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        _render_monitor([healthy, local], None, [], {}, clear=False)
    out = buf.getvalue()
    assert "shm!" not in out and " shm " in out, out
    assert "local" in out and "loca!" not in out, out


def test_shm_pin_on_fan_role_node_rejected():
    """An explicit ``tier="shm"`` pin on a replica/branch/fan-out node
    is rejected loudly (at construction, and re-checked after a deploy
    message mutates the role) — the fan machinery is wire-framed by
    design, so the ladder would be silently skipped with
    ``tier_fallbacks`` still 0 (the exact ambiguity the per-hop
    fallback counter exists to remove).  ``auto`` stays allowed:
    riding tcp there is policy, not degradation."""
    from defer_tpu.runtime.node import StageNode, _normalize_hop_tiers
    with pytest.raises(ValueError, match="replica"):
        StageNode(None, "127.0.0.1:0", None, tier="shm", replica=0)
    with pytest.raises(ValueError, match="branch"):
        StageNode(None, "127.0.0.1:0", None, tier="shm", branch=1)
    with pytest.raises(ValueError, match="fan-out"):
        StageNode(None, "127.0.0.1:0", "127.0.0.1:1,127.0.0.1:2",
                  tier="shm")
    StageNode(None, "127.0.0.1:0", None, tier="auto", replica=0)
    # the deploy handler re-runs the same check after applying the
    # message, so an in-band role change cannot sneak past the pin
    node = StageNode(None, "127.0.0.1:0", None, tier="shm")
    node.replica = 0  # what {"cmd": "deploy", "replica": 0} sets
    with pytest.raises(ValueError, match="replica"):
        node._check_tier_pin()
    # a chain-WIDE tier="shm" default hits the same adjacency guard as
    # an explicit hop_tiers entry when a stage is replicated
    with pytest.raises(ValueError, match="replicated"):
        _normalize_hop_tiers(None, 3, [1, 2, 1], "shm")
    assert _normalize_hop_tiers(None, 3, [1, 2, 1], "auto") \
        == ["auto", "auto"]


def test_shm_hop_tiers_require_overlap(tiny):
    """Satellite: an explicit shm claim under the serial (pure-wire)
    loop is rejected loudly — it would silently run full codec + TCP
    under a tier claim (mirror of the local+serial guard)."""
    from defer_tpu.runtime.node import run_chain
    g, params = tiny
    stages = partition(g, num_stages=3)
    with pytest.raises(ValueError, match="shm.*overlap|overlap.*shm"):
        run_chain(stages, params, [], hop_tiers=["shm", "shm"],
                  overlap=False)


def test_shm_hop_adjacent_to_replica_rejected():
    from defer_tpu.runtime.node import _normalize_hop_tiers
    with pytest.raises(ValueError, match="replicated"):
        _normalize_hop_tiers(["shm", "tcp"], 3, [1, 2, 1], "tcp")
    assert _normalize_hop_tiers(["shm", "auto"], 3, [1, 1, 1], "tcp") \
        == ["shm", "auto"]


# ---------------------------------------------------------------------------
# segment lifecycle: orphan sweep
# ---------------------------------------------------------------------------

def test_sweep_reaps_dead_pid_segments_only():
    if not os.path.isdir("/dev/shm"):
        pytest.skip("no /dev/shm on this host")
    from multiprocessing import shared_memory

    # a plausibly-dead pid: max pid space is rarely saturated
    dead = f"{SEG_PREFIX}999999_deadbeef0000"
    alive = f"{SEG_PREFIX}{os.getpid()}_feedfeed0000"
    for name in (dead, alive):
        seg = shared_memory.SharedMemory(name=name, create=True, size=64)
        seg.close()
    try:
        reaped = sweep_orphan_segments()
        assert dead in reaped
        assert not os.path.exists(f"/dev/shm/{dead}")
        # own-pid segments are never swept (this process's rings reap
        # themselves)
        assert os.path.exists(f"/dev/shm/{alive}")
    finally:
        for name in (dead, alive):
            try:
                os.unlink(f"/dev/shm/{name}")
            except OSError:
                pass


# ---------------------------------------------------------------------------
# planner: the shm pseudo-codec
# ---------------------------------------------------------------------------

def _fat_boundary_model():
    from defer_tpu import GraphBuilder
    from defer_tpu.graph import ops
    from defer_tpu.plan import StageCostModel

    b = GraphBuilder("fatcut")
    x = b.input((4096,))
    for i in range(3):
        x = b.add(ops.Dense(4096), x, name=f"d{i}")
    x = b.add(ops.Dense(8), x, name="head")
    g = b.build()
    costs = {"d0": 1e-3, "d1": 1e-3, "d2": 1e-3, "head": 1e-4}
    return g, StageCostModel(g, gen="v4", link_bw_s=1e6, node_costs=costs)


def test_solver_exploits_shm_hop_tier_map():
    """Acceptance bar: with a shm hop-tier map the solver places cuts
    across a fat boundary the all-tcp plan avoids — strict predicted
    bottleneck win on this comm-bound model — and the tier survives the
    plan-JSON roundtrip."""
    from defer_tpu.plan import plan_from_json, solve

    g, cm = _fat_boundary_model()
    p_tcp = solve(g, 3, cm)
    tiers = {c: "shm" for c in ("d0", "d1", "d2")}
    p_shm = solve(g, 3, cm, hop_tiers=tiers)
    assert p_shm.bottleneck_s < p_tcp.bottleneck_s  # STRICT: comm-bound
    assert set(p_shm.codecs) == {"shm"}
    assert p_shm.hop_tiers == ["shm"] * 2
    doc = p_shm.to_json()
    assert doc["hop_tiers"] == ["shm", "shm"]
    assert plan_from_json(doc).hop_tiers == ["shm", "shm"]


def test_shm_costs_between_local_and_wire():
    """The ladder's preference order falls out of the model: local
    (one pass + host sync) < shm (two passes + host sync) < any wire
    codec on a fat boundary — the tiers differ by exactly one
    memory-bandwidth pass over the boundary bytes (the host_sync
    round-trip they BOTH pay is the part the ici tier removes,
    tests/test_ici.py)."""
    g, cm = _fat_boundary_model()
    local_s = cm.with_hop_tiers({"d1": "local"}).comm_seconds("d1", "local")
    shm_s = cm.with_hop_tiers({"d1": "shm"}).comm_seconds("d1", "shm")
    wire_s = cm.best_codec("d1")[1]
    assert 0.0 < local_s < shm_s < wire_s
    assert shm_s - local_s == pytest.approx(
        cm.cut_bytes("d1") / cm.local_bw_s)


def test_shm_tier_never_applies_to_fan_hops():
    g, cm = _fat_boundary_model()
    cm = cm.with_hop_tiers({"d1": "shm"})
    name, s = cm.best_codec_replicated("d1", 1, 1)
    assert name == "shm"
    name2, s2 = cm.best_codec_replicated("d1", 2, 1)
    assert name2 != "shm" and s2 > s


def test_replan_preserves_shm_hop_tiers():
    from defer_tpu.plan import replan, solve

    g, cm = _fat_boundary_model()
    tiers = {c: "shm" for c in ("d0", "d1", "d2")}
    plan = solve(g, 3, cm, hop_tiers=tiers)
    rp = replan(g, plan, {0: 2e-3, 1: 1e-3, 2: 1e-3},
                cm.with_hop_tiers(tiers))
    assert set(rp.new_plan.hop_tiers) == {"shm"}
    assert set(rp.old_plan_corrected.hop_tiers) == {"shm"}


# ---------------------------------------------------------------------------
# real OS processes: end-to-end negotiation (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_real_process_shm_grant_end_to_end(tiny):
    """Full mode: 3 separate OS processes, every hop (dispatcher edges
    included) negotiated shm via the probe, byte-identical to all-TCP,
    no segments left behind."""
    from defer_tpu.runtime.node import run_chain

    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(11)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(4)]
    before = _segments()
    stats: list = []
    outs = run_chain(stages, params, xs, hop_tiers=["shm", "shm"],
                     tier="shm", env=CPU_ENV, stats_out=stats)
    assert {(s["tier"], s["tier_in"]) for s in stats} == {("shm", "shm")}
    base = run_chain(stages, params, xs, tier="tcp", env=CPU_ENV)
    for a, b in zip(base, outs):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert _segments() <= before, "real-process chain leaked /dev/shm"


@pytest.mark.slow
def test_real_process_kill9_poisons_cleanly_no_orphans(tiny, monkeypatch):
    """kill -9 one stage mid-stream on an all-shm chain: the chain
    fails (no hang past the dispatcher's timeout budget — shrunk here
    so the test is fast), every child is terminated, and — the
    lifecycle bar — no shared-memory segment survives the teardown
    (the killed process skipped every unlink path; its neighbors and
    the sweep reap for it)."""
    from defer_tpu.runtime.node import run_chain

    # the kill can land before stage2 ever dials the result server
    # back; the failure then surfaces on the result-accept timeout —
    # 180 s by default, pointlessly slow for a test that asserts
    # "fails, not hangs"
    monkeypatch.setattr(ChainDispatcher, "timeout_s", 30.0)
    g, params = tiny
    stages = partition(g, num_stages=3)
    rng = np.random.default_rng(13)
    spawned: list = []
    before = _segments()

    def on_spawn(procs):
        spawned.extend(procs)

    def inputs():
        for i in range(40):
            if i == 2:
                spawned[1].kill()  # SIGKILL: no atexit, no unlink
            yield rng.standard_normal((1, 32, 32, 3)).astype(np.float32)

    t0 = time.monotonic()
    with pytest.raises(RuntimeError):
        run_chain(stages, params, inputs(), hop_tiers=["shm", "shm"],
                  tier="shm", env=CPU_ENV, on_spawn=on_spawn,
                  spawn_retries=1)
    assert time.monotonic() - t0 < 150, "kill-9 teardown hung"
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if all(pr.poll() is not None for pr in spawned):
            break
        time.sleep(0.2)
    assert all(pr.poll() is not None for pr in spawned)
    # surviving ends reaped inline; whatever ONLY the dead process knew
    # about is the sweep's job — run it as the next deploy would
    sweep_orphan_segments()
    assert _segments() <= before, (
        f"kill -9 leaked segments: {_segments() - before}")
