"""Speculative decoding: token-exact greedy equivalence.

The invariant that makes speculation safe: for ANY draft model, greedy
speculative output == target-only greedy output, token for token.  The
draft only changes how much work is spent, never what is produced.
"""

import numpy as np
import pytest

import jax

from defer_tpu import Defer, DeferConfig, speculative_generate
from defer_tpu.models.gpt import gpt

VOCAB = 61
T_MODEL = 32


@pytest.fixture(scope="module")
def pair():
    target = gpt(4, 32, 2, T_MODEL, vocab=VOCAB, name="spec_target")
    tparams = target.init(jax.random.key(0))
    draft = gpt(2, 16, 2, T_MODEL, vocab=VOCAB, name="spec_draft")
    dparams = draft.init(jax.random.key(1))
    return target, tparams, draft, dparams


def reference_greedy(graph, params, prompt, max_new):
    """Target-only greedy via full recompute per token (oracle)."""
    out = np.array(prompt)
    fwd = jax.jit(graph.apply)
    for _ in range(max_new):
        logits = np.asarray(fwd(params, out.astype(np.int32)))
        nxt = np.argmax(logits[:, out.shape[1] - 1], axis=-1)
        out = np.concatenate([out, nxt[:, None].astype(out.dtype)], axis=1)
    return out


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_token_exact_vs_target_greedy(pair, gamma):
    target, tparams, draft, dparams = pair
    defer = Defer(config=DeferConfig(microbatch=2, chunk=4))
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, VOCAB, (4, 5)).astype(np.int64)
    got, stats = speculative_generate(
        defer, target, tparams, draft, dparams, prompt, 10,
        gamma=gamma, num_stages=4, draft_num_stages=2, return_stats=True)
    want = reference_greedy(target, tparams, prompt, 10)
    np.testing.assert_array_equal(got, want)
    assert stats["rounds"] >= 1 and stats["target_forwards"] >= 1
    assert 0.0 <= stats["accept_rate"] <= 1.0


def test_perfect_draft_accepts_everything(pair):
    """Draft == target: every proposal accepted; each round advances
    gamma+1 tokens, so target forwards ~ max_new / (gamma+1)."""
    target, tparams, _, _ = pair
    defer = Defer(config=DeferConfig(microbatch=2, chunk=4))
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, VOCAB, (2, 4)).astype(np.int64)
    gamma, new = 4, 12
    got, stats = speculative_generate(
        defer, target, tparams, target, tparams, prompt, new,
        gamma=gamma, num_stages=4, draft_num_stages=4, return_stats=True)
    np.testing.assert_array_equal(
        got, reference_greedy(target, tparams, prompt, new))
    assert stats["accept_rate"] == 1.0
    assert stats["target_forwards"] <= -(-new // (gamma + 1)) + 1


def test_eos_freezes_sequence(pair):
    target, tparams, draft, dparams = pair
    defer = Defer(config=DeferConfig(microbatch=2, chunk=4))
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, VOCAB, (2, 4)).astype(np.int64)
    # pick the actual 2nd greedy token as "eos" so it must trigger
    ref = reference_greedy(target, tparams, prompt, 10)
    eos = int(ref[0, 5])
    got = speculative_generate(
        defer, target, tparams, draft, dparams, prompt, 10,
        gamma=3, eos_id=eos, num_stages=4, draft_num_stages=2)
    row = got[0, 4:]
    hits = np.where(row == eos)[0]
    assert hits.size and (row[hits[0]:] == eos).all()
    # pre-EOS tokens still exactly match the target-only greedy stream
    np.testing.assert_array_equal(got[0, :4 + hits[0] + 1],
                                  ref[0, :4 + hits[0] + 1])


def test_validation_errors(pair):
    target, tparams, draft, dparams = pair
    defer = Defer(config=DeferConfig(microbatch=2, chunk=4))
    prompt = np.zeros((2, 4), np.int64)
    with pytest.raises(ValueError, match="gamma"):
        speculative_generate(defer, target, tparams, draft, dparams,
                             prompt, 4, gamma=0)
    with pytest.raises(ValueError, match="exceeds"):
        speculative_generate(defer, target, tparams, draft, dparams,
                             prompt, T_MODEL, num_stages=4)
