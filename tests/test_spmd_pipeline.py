"""SPMD pipeline engine tests on the 8-device CPU mesh.

The load-bearing invariant: pipeline output == single-program output, for
every stage count, chunking, partial chunks, and data-parallel meshes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from defer_tpu import SpmdPipeline, partition, pipeline_mesh
from defer_tpu.models import resnet_tiny
from defer_tpu.graph.analysis import auto_cut_points


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    return g, params


def _reference(g, params, inputs):
    fn = jax.jit(g.apply)
    return np.stack([np.asarray(fn(params, x), np.float32) for x in inputs])


@pytest.mark.parametrize("num_stages", [1, 2, 4, 8])
def test_pipeline_matches_single_program(tiny, num_stages):
    g, params = tiny
    stages = partition(g, num_stages=num_stages)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(num_stages),
                        microbatch=2, chunk=4)
    inputs = np.asarray(
        jax.random.normal(jax.random.key(7), (6, 2, 32, 32, 3)))
    out = pipe.run(inputs)
    ref = _reference(g, params, inputs)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_reweight_live_pipeline(tiny):
    """Weights-only re-push on the SPMD engine: new params install into
    the live flat buffer with NO recompile, outputs match the single
    program under the new weights, and shape mismatches are refused."""
    g, params = tiny
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=2, chunk=4)
    inputs = np.asarray(
        jax.random.normal(jax.random.key(9), (4, 2, 32, 32, 3)))
    np.testing.assert_allclose(pipe.run(inputs), _reference(g, params, inputs),
                               rtol=2e-4, atol=2e-4)
    program_before = pipe._chunk_fn  # the compiled chunk program

    params2 = jax.tree.map(lambda a: a * 1.25, params)
    pipe.reweight(params2)
    out2 = pipe.run(inputs)
    np.testing.assert_allclose(out2, _reference(g, params2, inputs),
                               rtol=2e-4, atol=2e-4)
    # no recompile: the jitted chunk program object is untouched
    assert pipe._chunk_fn is program_before

    # pushing the originals back restores the original outputs
    pipe.reweight(params)
    np.testing.assert_allclose(pipe.run(inputs),
                               _reference(g, params, inputs),
                               rtol=2e-4, atol=2e-4)

    bad = dict(params2)
    first = next(k for k, v in bad.items() if v)
    bad[first] = jax.tree.map(lambda a: np.zeros((3, 3), np.float32),
                              bad[first])
    with pytest.raises(ValueError, match="reweight"):
        pipe.reweight(bad)


def test_partial_chunks_and_streaming(tiny):
    g, params = tiny
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=8)
    # M=3 < chunk forces bubble padding; M=11 forces a partial tail chunk
    for m in (3, 11):
        inputs = np.asarray(
            jax.random.normal(jax.random.key(m), (m, 1, 32, 32, 3)))
        out = pipe.run(inputs)
        ref = _reference(g, params, inputs)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_push_flush_incremental(tiny):
    g, params = tiny
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=2)
    inputs = np.asarray(
        jax.random.normal(jax.random.key(3), (5, 1, 32, 32, 3)))
    pipe.reset()
    outs = []
    for lo in range(0, 4, 2):
        outs.extend(pipe.push(inputs[lo:lo + 2]))
    outs.extend(pipe.push(np.concatenate(
        [inputs[4:5], np.zeros_like(inputs[:1])]), n_real=1))
    outs.extend(pipe.flush())
    assert len(outs) == 5
    ref = _reference(g, params, inputs)
    got = np.stack([np.asarray(o, np.float32) for o in outs])
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_data_parallel_mesh(tiny):
    g, params = tiny
    stages = partition(g, num_stages=4)
    mesh = pipeline_mesh(4, data_parallel=2)
    pipe = SpmdPipeline(stages, params, mesh=mesh, microbatch=4, chunk=4)
    inputs = np.asarray(
        jax.random.normal(jax.random.key(9), (5, 4, 32, 32, 3)))
    out = pipe.run(inputs)
    ref = _reference(g, params, inputs)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_bfloat16_buffer_close(tiny):
    """bf16 transfer buffer = the TPU analogue of lossy ZFP compression."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=4, buffer_dtype=jnp.bfloat16)
    inputs = np.asarray(jax.random.normal(jax.random.key(5), (4, 1, 32, 32, 3)))
    out = pipe.run(inputs)
    ref = _reference(g, params, inputs)
    np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.15)


def test_metrics_recorded(tiny):
    g, params = tiny
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=4)
    inputs = np.zeros((8, 1, 32, 32, 3), np.float32)
    pipe.run(inputs)
    m = pipe.metrics
    assert m.inferences == 8
    assert m.num_stages == 4
    assert m.wall_s > 0
    assert m.throughput > 0
    lats = pipe.stage_latencies(params, iters=2)
    assert len(lats) == 4 and all(l > 0 for l in lats)


def test_int_inputs_require_f32_buffer():
    """Token-id inputs through a bf16 buffer would silently corrupt ids>256."""
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.graph import ops

    b = GraphBuilder("toy_embed")
    x = b.input((4,), jnp.int32)
    e = b.add(ops.Embedding(vocab=300, features=8), x, name="embed")
    b.add(ops.Dense(4), e, name="head")
    g = b.build()
    params = g.init(jax.random.key(0))
    stages = partition(g, ["embed"])
    with pytest.raises(ValueError, match="float32"):
        SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                     buffer_dtype=jnp.bfloat16)
    # float32 buffer carries ids exactly
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2), chunk=2)
    inputs = np.array([[[1, 299, 5, 257]]], np.int32).repeat(2, axis=0)
    out = pipe.run(inputs)
    ref = np.asarray(jax.jit(g.apply)(params, jnp.asarray(inputs[0])))
    np.testing.assert_allclose(out[0], ref, rtol=1e-5, atol=1e-5)


def test_scan_output_cropped_to_final_stage(tiny):
    """The per-step scan output carries only the final stage's output slice,
    not the whole transfer buffer (VERDICT r1: ~100 MB/chunk of dead stores
    on ResNet50 when the full [T, B, buf_elems] buffer was stacked)."""
    g, params = tiny
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=4)
    out_sz = stages[-1].out_spec.size
    assert out_sz < pipe.buf_elems  # the crop must actually save something
    a_aval = jax.ShapeDtypeStruct(pipe._a.shape, pipe._a.dtype)
    xs_aval = jax.ShapeDtypeStruct((pipe.chunk, 1, pipe.buf_elems),
                                   pipe.buffer_dtype)
    _, outs = jax.eval_shape(pipe._chunk_fn, pipe._w, a_aval, xs_aval)
    assert outs.shape == (4, pipe.chunk, 1, out_sz)


def test_weight_buffer_stored_in_compute_dtype(tiny):
    """compute_dtype deployments hold the weight buffer in that dtype
    (half the HBM for bf16) and still match the f32 program closely."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=4,
                        buffer_dtype=jnp.bfloat16,
                        compute_dtype=jnp.bfloat16)
    assert pipe._w.dtype == jnp.bfloat16
    inputs = np.asarray(jax.random.normal(jax.random.key(2), (4, 1, 32, 32, 3)))
    out = pipe.run(inputs)
    ref = _reference(g, params, inputs)
    np.testing.assert_allclose(out, ref, rtol=0.15, atol=0.2)
    # f32 default unchanged
    pipe32 = SpmdPipeline(stages, params, mesh=pipeline_mesh(2))
    assert pipe32._w.dtype == jnp.float32


def test_int_param_leaf_guard():
    """Integer param leaves must survive the weight buffer exactly or fail
    loudly (the flat-buffer abstraction's round-trip trap, VERDICT r1)."""
    from defer_tpu.graph.ir import GraphBuilder
    from defer_tpu.graph import ops

    b = GraphBuilder("toy_embed")
    x = b.input((4,), jnp.int32)
    e = b.add(ops.Embedding(vocab=300, features=8), x, name="embed")
    b.add(ops.Dense(4), e, name="head")
    g = b.build()
    params = g.init(jax.random.key(0))
    # graft an int32 leaf that cannot survive a bf16 buffer
    params = dict(params)
    params["embed"] = dict(params["embed"], steps=np.array([1, 301, 7], np.int32))
    stages = partition(g, ["embed"])
    with pytest.raises(ValueError, match="non-float param leaf"):
        SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                     compute_dtype=jnp.bfloat16)
    # exact in the f32 buffer -> accepted
    SpmdPipeline(stages, params, mesh=pipeline_mesh(2))


def test_raw_push_matches_per_step_collection(tiny):
    """raw=True must deliver the same microbatches as the per-step path —
    one device slab per chunk, bubbles flagged in the mask."""
    g, params = tiny
    stages = partition(g, num_stages=4)
    mk = lambda: SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                              microbatch=1, chunk=4)
    inputs = np.asarray(
        jax.random.normal(jax.random.key(5), (4, 1, 32, 32, 3)))

    ref_pipe = mk()
    ref_pipe.reset()
    ref_outs = ref_pipe.push(inputs)
    ref_outs.extend(ref_pipe.flush())

    pipe = mk()
    pipe.reset()
    got = []
    slab, mask = pipe.push(inputs, raw=True)
    if slab is not None:
        arr = np.asarray(slab, np.float32)
        got.extend(arr[i] for i in range(len(mask)) if mask[i])
    # drain with raw bubble pushes — bounded so a raw-path regression
    # fails the test instead of hanging it
    bubbles = np.zeros_like(inputs)
    for _ in range(4):
        if len(got) >= 4:
            break
        slab, mask = pipe.push(bubbles, n_real=0, raw=True)
        if slab is not None:
            arr = np.asarray(slab, np.float32)
            got.extend(arr[i] for i in range(len(mask)) if mask[i])

    assert len(got) == len(ref_outs) == 4
    for a, b in zip(got, ref_outs):
        np.testing.assert_allclose(
            a.reshape(np.asarray(b).shape), np.asarray(b),
            rtol=1e-5, atol=1e-5)
    assert pipe.metrics.inferences == 4
