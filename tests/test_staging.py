"""Native host staging ring + the network endpoint built on it.

The data-plane parity tests: the reference's bounded ingest queue + socket
loops (src/node.py:80-91,114; src/dispatcher.py:85-105) are here a C++
ring (``_native/staging.cpp``) and one full-duplex framed endpoint.
"""

import threading
import time

import numpy as np
import pytest

import jax

from defer_tpu import Defer, DeferConfig
from defer_tpu.models import resnet_tiny
from defer_tpu.transport.framed import TensorClient
from defer_tpu.transport.staging import HostStagingRing, _load


def test_native_library_builds():
    """The C++ staging ring must actually compile here — the fallback is
    for toolchain-less user machines, not for CI."""
    assert _load() is not None


@pytest.mark.parametrize("native", [True, False])
def test_ring_push_pop_layout(native, monkeypatch):
    if not native:
        monkeypatch.setattr("defer_tpu.transport.staging._load",
                            lambda: None)
    ring = HostStagingRing(slot_elems=8, n_slots=4)
    assert ring.is_native == native
    ring.push(np.arange(5, dtype=np.float32))        # short: zero-padded
    ring.push(np.arange(8, dtype=np.float32) + 100)  # exact size
    got, block = ring.pop_block(4)
    assert got == 2 and block.shape == (4, 8)
    np.testing.assert_array_equal(block[0], [0, 1, 2, 3, 4, 0, 0, 0])
    np.testing.assert_array_equal(block[1], np.arange(8) + 100)
    np.testing.assert_array_equal(block[2:], 0)      # bubble tail
    with pytest.raises(ValueError, match="exceeds slot"):
        ring.push(np.zeros(9, np.float32))


def test_ring_close_drain_and_timeout():
    ring = HostStagingRing(slot_elems=4, n_slots=2)
    ring.push(np.ones(4, np.float32))
    ring.close()
    got, block = ring.pop_block(2)
    assert got == 1                       # backlog drains after close
    got, block = ring.pop_block(2)
    assert got == 0 and block is None     # then end-of-stream
    with pytest.raises(ValueError, match="closed"):
        ring.push(np.ones(4, np.float32))
    ring2 = HostStagingRing(slot_elems=4, n_slots=2)
    with pytest.raises(TimeoutError):
        ring2.pop_block(1, timeout_s=0.05)


def test_ring_backpressure_blocks_producer():
    """A full ring blocks push (bounded in-flight window) until the
    consumer drains — and the block is bounded, not forever."""
    ring = HostStagingRing(slot_elems=4, n_slots=2)
    assert ring.push(np.ones(4, np.float32), timeout_s=1.0)
    assert ring.push(np.ones(4, np.float32), timeout_s=1.0)
    t0 = time.perf_counter()
    assert not ring.push(np.ones(4, np.float32), timeout_s=0.2)  # timeout
    assert 0.15 < time.perf_counter() - t0 < 5.0

    def drain():
        time.sleep(0.2)
        ring.pop_block(2)

    threading.Thread(target=drain, daemon=True).start()
    assert ring.push(np.ones(4, np.float32), timeout_s=5.0)  # unblocked


def test_serve_endpoint_streams_in_order():
    """Full-duplex endpoint: framed tensors in, pipelined results out, all
    in feed order, vs the single-program oracle."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(microbatch=1, chunk=4))
    address, thread = defer.serve_endpoint(g, params, num_stages=4)

    rng = np.random.default_rng(0)
    xs = [rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
          for _ in range(10)]
    client = TensorClient(*address)
    outs = client.infer_stream(xs)
    client.close()
    thread.join(timeout=60)

    assert len(outs) == 10
    fwd = jax.jit(g.apply)
    for x, y in zip(xs, outs):
        np.testing.assert_allclose(y, np.asarray(fwd(params, x)),
                                   rtol=2e-4, atol=2e-4)
