"""Concurrency stress: many in-flight microbatches, concurrent producer /
consumer threads against the queue service.

The reference's only cross-thread discipline is one mutex around NodeState
plus queue.Queue hand-offs (src/node_state.py:12-41); SURVEY.md §5 (race
row) calls for a deterministic stress test of the host-side streaming path.
Outputs must arrive exactly once, in feed order, with correct values, under
producer/consumer timing jitter.
"""

import queue
import threading
import time

import numpy as np

import jax

from defer_tpu import Defer, DeferConfig, END_OF_STREAM
from defer_tpu.models import resnet_tiny


def test_streaming_order_and_exactly_once_under_jitter():
    g = resnet_tiny()
    p = g.init(jax.random.key(0))
    n = 64
    xs = np.random.default_rng(0).normal(
        size=(n, 32, 32, 3)).astype(np.float32)
    # identity on the output's argmax won't do — compare full outputs
    ref = np.stack([np.asarray(jax.jit(g.apply)(p, x[None])[0])
                    for x in xs[:4]])

    defer = Defer(config=DeferConfig(microbatch=1, chunk=4,
                                     gather_timeout_s=0.001))
    in_q: queue.Queue = queue.Queue(maxsize=8)   # bounded, like test/test.py
    out_q: queue.Queue = queue.Queue()
    h = defer.run_defer(g, p, None, in_q, out_q, num_stages=4)

    def produce():
        rng = np.random.default_rng(1)
        for i in range(n):
            in_q.put(xs[i])
            if rng.random() < 0.3:
                time.sleep(rng.random() * 0.01)  # bursty producer
        in_q.put(END_OF_STREAM)

    got = []

    def consume():
        while True:
            o = out_q.get(timeout=120)
            if o is END_OF_STREAM:
                return
            got.append(np.asarray(o))

    tp = threading.Thread(target=produce)
    tc = threading.Thread(target=consume)
    tp.start(); tc.start()
    tp.join(timeout=300)
    h.join(timeout=300)
    out_q.put(END_OF_STREAM)
    tc.join(timeout=300)

    assert h.healthy
    assert len(got) == n                       # exactly once, none lost
    for i in range(4):                         # spot-check order + values
        np.testing.assert_allclose(got[i][0], ref[i], rtol=1e-4, atol=1e-4)
    # full-order check: outputs must match their own input's single-device
    # result at matching index (cheap verify on a stride)
    for i in range(0, n, 16):
        want = np.asarray(jax.jit(g.apply)(p, xs[i][None])[0])
        np.testing.assert_allclose(got[i][0], want, rtol=1e-4, atol=1e-4)


def test_streaming_mpmd_mode_under_jitter():
    """The same exactly-once / in-order contract with the MPMD fallback
    engine serving the queue (mode='mpmd' is a real fallback, not just a
    batch oracle)."""
    g = resnet_tiny()
    p = g.init(jax.random.key(0))
    n = 24
    xs = np.random.default_rng(3).normal(
        size=(n, 1, 32, 32, 3)).astype(np.float32)

    defer = Defer(config=DeferConfig(microbatch=1, mode="mpmd"))
    in_q: queue.Queue = queue.Queue(maxsize=6)
    out_q: queue.Queue = queue.Queue()
    h = defer.run_defer(g, p, None, in_q, out_q, num_stages=4)

    def produce():
        rng = np.random.default_rng(4)
        for i in range(n):
            in_q.put(xs[i])
            if rng.random() < 0.3:
                time.sleep(rng.random() * 0.005)
        in_q.put(END_OF_STREAM)

    got = []

    def consume():
        while True:
            o = out_q.get(timeout=120)
            if o is END_OF_STREAM:
                return
            got.append(np.asarray(o))

    tp = threading.Thread(target=produce)
    tc = threading.Thread(target=consume)
    tp.start(); tc.start()
    tp.join(timeout=300)
    h.join(timeout=300)
    out_q.put(END_OF_STREAM)
    tc.join(timeout=300)

    assert h.healthy
    assert len(got) == n
    for i in range(0, n, 8):
        want = np.asarray(jax.jit(g.apply)(p, xs[i]))
        np.testing.assert_allclose(got[i], want, rtol=1e-4, atol=1e-4)
