"""Tensor parallelism: sharded forward == unsharded forward.

The capability invariant mirrors the partitioner's (stitched stages == full
model, reference src/dag_util.py:27-31), applied to the intra-layer axis:
Megatron-sharded execution over the "model" mesh axis must reproduce the
single-device forward bit-for-bit up to float tolerance.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu import GraphBuilder, shard_tp_params, tensor_parallel_fn
from defer_tpu.graph.ops import Dense, Activation, TransformerBlock
from defer_tpu.models import bert_tiny
from defer_tpu.parallel.tensor import tensor_parallel_mesh


def mlp_graph(d=16, h=64, out=8):
    b = GraphBuilder("mlp")
    x = b.input((d,))
    x = b.add(Dense(h), x)
    x = b.add(Activation("relu"), x)
    x = b.add(Dense(out), x)
    return b.build()


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_dense_tp_matches_full(tp):
    graph = mlp_graph()
    params = graph.init(jax.random.key(0))
    x = np.random.default_rng(1).normal(size=(3, 16)).astype(np.float32)

    ref = graph.apply(params, jnp.asarray(x))
    mesh = tensor_parallel_mesh(tp)
    stk = shard_tp_params(graph, params, tp, mesh=mesh)
    out = tensor_parallel_fn(graph, mesh)(stk, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("tp", [2])
def test_bert_tp_matches_full(tp):
    graph = bert_tiny()
    params = graph.init(jax.random.key(0))
    ids = np.arange(2 * 16).reshape(2, 16) % 100

    ref = graph.apply(params, jnp.asarray(ids))
    mesh = tensor_parallel_mesh(tp)
    stk = shard_tp_params(graph, params, tp, mesh=mesh)
    out = tensor_parallel_fn(graph, mesh)(stk, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_tp_weight_shards_are_disjoint():
    """Each rank holds 1/tp of every sharded matrix (memory actually
    scales down, the point of TP)."""
    graph = bert_tiny()
    params = graph.init(jax.random.key(0))
    tp = 2
    blk = graph.nodes["block_0"].op.tp_shard(params["block_0"], tp, 0)
    full = params["block_0"]
    assert blk["qkv"]["w"].shape[1] * tp == full["qkv"]["w"].shape[1]
    assert blk["proj"]["w"].shape[0] * tp == full["proj"]["w"].shape[0]
    assert blk["fc1"]["w"].shape[1] * tp == full["fc1"]["w"].shape[1]
    assert blk["fc2"]["w"].shape[0] * tp == full["fc2"]["w"].shape[0]


def test_tp_indivisible_heads_raises():
    graph = bert_tiny()  # 2 heads
    params = graph.init(jax.random.key(0))
    with pytest.raises(ValueError, match="not divisible"):
        graph.nodes["block_0"].op.tp_shard(params["block_0"], 3, 0)


@pytest.mark.parametrize("tp,kv", [(2, 2), (2, 4), (4, 4)])
def test_gpt_gqa_tp_matches_full(tp, kv):
    """GQA causal blocks under Megatron TP: each rank holds whole query
    groups (nh/tp query heads, kv/tp KV heads); the sharded forward must
    equal the unsharded one.  heads=8 > kv everywhere, so every case
    exercises the GQA (not MHA) shard/apply paths, including tp=4."""
    from defer_tpu.models.gpt import gpt

    graph = gpt(2, 32, 8, 12, vocab=64, kv_heads=kv,
                name=f"gqa_tp{tp}_kv{kv}")
    params = graph.init(jax.random.key(5))
    ids = np.arange(2 * 12).reshape(2, 12) % 64

    ref = graph.apply(params, jnp.asarray(ids, jnp.int32))
    mesh = tensor_parallel_mesh(tp)
    stk = shard_tp_params(graph, params, tp, mesh=mesh)
    out = tensor_parallel_fn(graph, mesh)(stk, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gpt_gqa_tp_indivisible_kv_raises():
    from defer_tpu.models.gpt import gpt

    graph = gpt(1, 32, 4, 8, vocab=32, kv_heads=2, name="gqa_bad_tp")
    params = graph.init(jax.random.key(0))
    with pytest.raises(ValueError, match="not divisible"):
        graph.nodes["block_0"].op.tp_shard(params["block_0"], 4, 0)


def test_gpt_gqa_tp_shards_are_disjoint():
    from defer_tpu.models.gpt import gpt

    graph = gpt(1, 32, 4, 8, vocab=32, kv_heads=2, name="gqa_shard")
    params = graph.init(jax.random.key(0))
    full = params["block_0"]
    tp = 2
    blk = graph.nodes["block_0"].op.tp_shard(full, tp, 0)
    assert blk["qkv"]["w"].shape[1] * tp == full["qkv"]["w"].shape[1]
    assert blk["proj"]["w"].shape[0] * tp == full["proj"]["w"].shape[0]


@pytest.mark.parametrize("kv,tp", [(2, 2), (4, 2), (8, 2), (4, 4)])
def test_tp_unshard_inverts_tp_shard(kv, tp):
    """Exact inversion for MHA (kv==nh) and GQA: reassembling all ranks'
    shards must reproduce every leaf bit-for-bit — which also proves the
    per-rank column slices are disjoint and correctly offset (identical
    or mis-offset slices cannot reassemble to the original)."""
    from defer_tpu.models.gpt import CausalTransformerBlock
    from defer_tpu.graph.ir import ShapeSpec

    blk = CausalTransformerBlock(8, num_kv_heads=kv)
    p = blk.init(jax.random.key(4), (ShapeSpec((6, 32)),))
    shards = [blk.tp_shard(p, tp, r) for r in range(tp)]
    back = blk.tp_unshard(shards)
    flat_b, td_b = jax.tree.flatten(back)
    flat_p, td_p = jax.tree.flatten(p)
    assert td_b == td_p
    for a, b in zip(flat_b, flat_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
