"""Pipeline-parallel training: autodiff through the ppermute ring.

The load-bearing invariant mirrors the inference one: the loss and the
per-stage gradients of the pipelined program must equal those of the
single-program reference (JAX transposes the ppermute ring into the
backward wavefront; nothing bespoke to get wrong — but the scheduling,
masking, and buffer plumbing around it can be)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from defer_tpu import SpmdPipeline, partition, pipeline_mesh
from defer_tpu.models import resnet_tiny
from defer_tpu.runtime.training import PipelineTrainer


@pytest.fixture(scope="module")
def tiny():
    g = resnet_tiny()
    return g, g.init(jax.random.key(0))


def _loss(logits, labels):
    # mean cross-entropy over the microbatch
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[..., None],
                                         axis=-1))


@pytest.mark.parametrize("num_stages", [
    pytest.param(2, marks=pytest.mark.slow), 4])
def test_pipeline_grads_match_single_program(tiny, num_stages):
    g, params = tiny
    stages = partition(g, num_stages=num_stages)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(num_stages),
                        microbatch=2, chunk=4)
    trainer = PipelineTrainer(pipe, _loss)

    rng = np.random.default_rng(0)
    m = 3
    xs = rng.standard_normal((m, 2, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (m, 2))

    loss, grads = trainer.loss_and_grad(xs, ys)

    # single-program reference: summed per-microbatch loss
    def ref_loss(p):
        tot = 0.0
        for i in range(m):
            tot = tot + _loss(g.apply(p, xs[i]), jnp.asarray(ys[i]))
        return tot

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l),
                               rtol=1e-4, atol=1e-4)

    got_stage_grads = trainer.stage_grads(grads)
    for s, sg in zip(stages, got_stage_grads):
        want = {n: ref_g[n] for n in s.node_names if n in ref_g}
        flat_w, _ = jax.tree.flatten(want)
        flat_g, _ = jax.tree.flatten(sg)
        assert len(flat_w) == len(flat_g)
        for a, b in zip(flat_w, flat_g):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_accumulate_step_equals_one_big_chunk(tiny):
    """Gradient accumulation: K chunks then one update == the single-chunk
    update on the concatenated batch (same loss_fn sums per microbatch)."""
    import optax
    g, params = tiny
    rng = np.random.default_rng(1)
    xs = rng.standard_normal((4, 2, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (4, 2))

    def make_trainer(chunk):
        pipe = SpmdPipeline(partition(g, num_stages=2), params,
                            mesh=pipeline_mesh(2), microbatch=2,
                            chunk=chunk)
        return PipelineTrainer(pipe, _loss, optimizer=optax.sgd(1e-2))

    t_acc = make_trainer(2)
    loss_acc = t_acc.accumulate_step([(xs[:2], ys[:2]), (xs[2:], ys[2:])])
    t_one = make_trainer(4)
    loss_one = t_one.step(xs, ys)
    np.testing.assert_allclose(loss_acc, loss_one, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(t_acc.pipe._w),
                               np.asarray(t_one.pipe._w),
                               rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="at least one batch"):
        t_acc.accumulate_step([])


def test_train_step_reduces_loss(tiny):
    import optax

    g, params = tiny
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=4)
    # loss is SUMMED over the chunk's microbatches, so keep lr small
    trainer = PipelineTrainer(pipe, _loss, optimizer=optax.adam(1e-3))

    rng = np.random.default_rng(1)
    xs = rng.standard_normal((4, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (4, 1))
    losses = [trainer.step(xs, ys) for _ in range(8)]
    # overfitting 4 fixed samples: the tail must sit below the start
    assert min(losses[-3:]) < losses[0], losses


def test_trained_weights_serve_inference(tiny):
    """After training, the SAME pipeline (same weight buffer) serves
    inference — the train/serve loop shares one deployment."""
    import optax

    g, params = tiny
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=2)
    trainer = PipelineTrainer(pipe, _loss, optimizer=optax.sgd(0.05))
    rng = np.random.default_rng(2)
    xs = rng.standard_normal((2, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))
    trainer.step(xs, ys)

    out = pipe.run(xs)
    assert out.shape == (2, 1, 10)
    assert np.isfinite(out).all()


@pytest.mark.slow
def test_int8_wire_trains_straight_through(tiny):
    """wire='int8' trains via STE: the loss tracks the buffer-wire loss
    within quantization error, gradients point the same way, and a few
    adam steps reduce the quantized deployment's loss."""
    import optax

    g, params = tiny
    stages = partition(g, num_stages=2)

    def mk(wire):
        pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                            microbatch=1, chunk=3, wire=wire)
        return PipelineTrainer(pipe, _loss, optimizer=optax.adam(1e-3))

    rng = np.random.default_rng(11)
    xs = rng.standard_normal((2, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))

    tq, tb = mk("int8"), mk("buffer")
    lq, gq = tq.loss_and_grad(xs, ys)
    lb, gb = tb.loss_and_grad(xs, ys)
    assert abs(float(lq) - float(lb)) / abs(float(lb)) < 0.05
    a, b = np.asarray(gq).ravel(), np.asarray(gb).ravel()
    cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))
    assert cos > 0.98, cos  # STE grads align with the exact-wire grads

    losses = [tq.step(xs, ys) for _ in range(6)]
    assert min(losses[-2:]) < losses[0], losses


def test_training_with_data_parallel(tiny):
    """pp x dp training: the dp-sharded chunk's loss/grads must match the
    single-program reference over the full global microbatch."""
    g, params = tiny
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params,
                        mesh=pipeline_mesh(2, data_parallel=2),
                        microbatch=2, chunk=2)
    trainer = PipelineTrainer(pipe, _loss)
    rng = np.random.default_rng(3)
    xs = rng.standard_normal((2, 2, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 2))
    loss, grads = trainer.loss_and_grad(xs, ys)

    def ref_loss(p):
        # pmean over dp shards of per-shard chunk loss: each shard's
        # loss_fn sees its local half of the microbatch, and the shards
        # are averaged — so a mean-over-batch loss keeps per-sample
        # scaling no matter the dp factor
        tot = 0.0
        for i in range(2):
            for s in range(2):
                tot = tot + _loss(g.apply(p, xs[i, s:s + 1]),
                                  jnp.asarray(ys[i, s:s + 1])) / 2.0
        return tot

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l),
                               rtol=1e-4, atol=1e-4)
    got = trainer.stage_grads(grads)
    for s, sg in zip(stages, got):
        for a, b in zip(jax.tree.flatten({n: ref_g[n]
                                          for n in s.node_names
                                          if n in ref_g})[0],
                        jax.tree.flatten(sg)[0]):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_bert_training_grads_match(tiny):
    """Integer-token models train through the pipeline too: ids ride the
    f32 transfer buffer, the branch casts them back to int, and the
    embedding-gather gradient flows to the table."""
    from defer_tpu.models import bert_tiny

    g = bert_tiny()
    params = g.init(jax.random.key(4))
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=3)

    trainer = PipelineTrainer(pipe, _loss)
    rng = np.random.default_rng(5)
    xs = rng.integers(0, 90, (2, 1, 16)).astype(np.float32)  # token ids
    ys = rng.integers(0, 10, (2, 1))  # pooled class labels

    loss, grads = trainer.loss_and_grad(xs, ys)

    def ref_loss(p):
        tot = 0.0
        for i in range(2):
            tot = tot + _loss(g.apply(p, xs[i].astype(np.int32)),
                              jnp.asarray(ys[i]))
        return tot

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l),
                               rtol=1e-4, atol=1e-4)
    got = trainer.stage_grads(grads)
    for s, sg in zip(stages, got):
        want = {n: ref_g[n] for n in s.node_names if n in ref_g}
        for a, b in zip(jax.tree.flatten(want)[0],
                        jax.tree.flatten(sg)[0]):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=5e-3, atol=5e-3)


def test_training_with_tensor_parallel():
    """pp x tp training: loss matches the single program, and one sgd
    step moves the sharded weights exactly like the reference step
    (end-to-end gradient check without reassembling tp shards)."""
    import optax

    from defer_tpu.models import bert_tiny

    g = bert_tiny()
    params = g.init(jax.random.key(6))
    stages = partition(g, num_stages=2)
    lr = 0.05

    def build(p):
        return SpmdPipeline(stages, p,
                            mesh=pipeline_mesh(2, tensor_parallel=2),
                            microbatch=1, chunk=3)

    pipe = build(params)
    trainer = PipelineTrainer(pipe, _loss, optimizer=optax.sgd(lr))
    rng = np.random.default_rng(7)
    xs = rng.integers(0, 90, (2, 1, 16)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))

    def ref_loss(p):
        tot = 0.0
        for i in range(2):
            tot = tot + _loss(g.apply(p, xs[i].astype(np.int32)),
                              jnp.asarray(ys[i]))
        return tot

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    loss = trainer.step(xs, ys)  # loss + one sgd update on the tp buffer
    np.testing.assert_allclose(loss, float(ref_l), rtol=1e-4, atol=1e-4)

    # reference sgd step, then compare the pipelines' forward outputs
    new_params = jax.tree.map(lambda w, dg: w - lr * dg, params, ref_g)
    ref_pipe = build(new_params)
    got = pipe.run(xs)
    want = ref_pipe.run(xs)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_trained_params_roundtrip(tiny):
    """trained_params() returns a standard pytree: a FRESH deployment
    built from it serves the same outputs as the trained one."""
    import optax

    g, params = tiny
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=2)
    trainer = PipelineTrainer(pipe, _loss, optimizer=optax.sgd(0.01))
    rng = np.random.default_rng(8)
    xs = rng.standard_normal((2, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))
    trainer.step(xs, ys)

    exported = trainer.trained_params()
    assert set(exported) == set(params)
    pipe2 = SpmdPipeline(stages, exported, mesh=pipeline_mesh(2),
                         microbatch=1, chunk=2)
    np.testing.assert_allclose(pipe.run(xs), pipe2.run(xs),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_checkpoint_resume_matches_uninterrupted(tiny, tmp_path):
    """save/load_checkpoint: resumed training walks the same trajectory
    as uninterrupted training (weights AND optimizer moments restored)."""
    import os

    import optax

    g, params = tiny
    stages = partition(g, num_stages=2)

    def mk_trainer():
        pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                            microbatch=1, chunk=2)
        return PipelineTrainer(pipe, _loss, optimizer=optax.adam(1e-3))

    rng = np.random.default_rng(9)
    xs = rng.standard_normal((2, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))

    ref = mk_trainer()
    for _ in range(3):
        ref_loss_3 = ref.step(xs, ys)

    t1 = mk_trainer()
    t1.step(xs, ys)
    t1.step(xs, ys)
    ckpt = os.path.join(tmp_path, "train_ckpt")
    t1.save_checkpoint(ckpt)

    t2 = mk_trainer()
    t2.load_checkpoint(ckpt)
    resumed_loss_3 = t2.step(xs, ys)
    np.testing.assert_allclose(resumed_loss_3, ref_loss_3,
                               rtol=1e-5, atol=1e-5)


def test_checkpoint_before_first_step_restores(tiny, tmp_path):
    """A checkpoint saved before any step must restore (the optimizer
    state is initialized on save so the restore template matches)."""
    import os

    import optax

    g, params = tiny
    stages = partition(g, num_stages=2)

    def mk():
        pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                            microbatch=1, chunk=2)
        return PipelineTrainer(pipe, _loss, optimizer=optax.adam(1e-3))

    t = mk()
    ckpt = os.path.join(tmp_path, "fresh")
    t.save_checkpoint(ckpt)
    t2 = mk()
    t2.load_checkpoint(ckpt)  # raised 'checkpoint mismatch' pre-fix
    rng = np.random.default_rng(10)
    xs = rng.standard_normal((2, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))
    assert np.isfinite(t2.step(xs, ys))


@pytest.mark.slow
def test_master_weights_mixed_precision_training(tiny):
    """bf16-compute deployment with f32 master weights: the buffer stays
    f32 (optimizer precision), stages really compute in bf16 (fresh
    master-bf16 and plain-bf16 deployments produce near-bitwise equal
    outputs — both apply the identical weight downcast), and training
    tracks the pure-f32 trajectory closely."""
    import optax

    g, params = tiny
    stages = partition(g, num_stages=2)

    def mk(compute_dtype=None, master=False):
        pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                            microbatch=1, chunk=2,
                            compute_dtype=compute_dtype,
                            master_weights=master)
        return pipe, PipelineTrainer(pipe, _loss,
                                     optimizer=optax.sgd(1e-3))

    rng = np.random.default_rng(12)
    xs = rng.standard_normal((2, 1, 32, 32, 3)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))

    pipe_m, t_master = mk(jnp.bfloat16, master=True)
    assert pipe_m._w.dtype == jnp.float32  # master buffer is f32
    _, t_f32 = mk(None)

    for _ in range(3):
        lm = t_master.step(xs, ys)
        lf = t_f32.step(xs, ys)
    # bf16 forward quantization only: trajectories stay close
    assert abs(lm - lf) / abs(lf) < 0.05, (lm, lf)

    # fresh master-bf16 vs plain-bf16: bf16(w_f32) is bitwise the stored
    # bf16 weight, and both branches compute in bf16 — outputs must agree
    # to float-noise (this FAILS loudly if master mode silently computes
    # in f32: the gap would be at bf16-quantization magnitude)
    pipe_m2, _ = mk(jnp.bfloat16, master=True)
    pipe_bf, _ = mk(jnp.bfloat16, master=False)
    np.testing.assert_allclose(
        np.asarray(pipe_m2.run(xs), np.float32),
        np.asarray(pipe_bf.run(xs), np.float32), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("family", [
    "vgg_tiny",
    pytest.param("inception_tiny", marks=pytest.mark.slow),
    pytest.param("mobilenet_tiny", marks=pytest.mark.slow)])
def test_training_grads_match_across_families(family):
    """Every model family trains through the pipeline — including the
    branching-DAG Inception whose backward fans in across cut points."""
    from defer_tpu import models as M

    g = getattr(M, family)()
    params = g.init(jax.random.key(13))
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(2),
                        microbatch=1, chunk=2)
    trainer = PipelineTrainer(pipe, _loss)

    rng = np.random.default_rng(14)
    in_shape = pipe.in_spec.shape
    xs = rng.standard_normal((1, 1) + in_shape).astype(np.float32)
    ys = rng.integers(0, pipe.out_spec.shape[-1], (1, 1))

    loss, grads = trainer.loss_and_grad(xs, ys)
    ref_l, ref_g = jax.value_and_grad(
        lambda p: _loss(g.apply(p, xs[0]), jnp.asarray(ys[0])))(params)
    np.testing.assert_allclose(float(loss), float(ref_l),
                               rtol=1e-3, atol=1e-3)
    for s, sg in zip(stages, trainer.stage_grads(grads)):
        want = {n: ref_g[n] for n in s.node_names if n in ref_g}
        for a, b in zip(jax.tree.flatten(want)[0],
                        jax.tree.flatten(sg)[0]):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-2, atol=2e-2)


def test_trained_params_roundtrip_tensor_parallel():
    """trained_params() under pp x tp: the op-level shard reassembly
    (tp_unshard) must invert tp_shard exactly pre-training, and a fresh
    UNSHARDED deployment built from post-training exported params must
    serve the same outputs as the trained tp deployment."""
    import optax

    from defer_tpu.models import bert_tiny

    g = bert_tiny()
    params = g.init(jax.random.key(11))
    stages = partition(g, num_stages=2)
    pipe = SpmdPipeline(stages, params,
                        mesh=pipeline_mesh(2, tensor_parallel=2),
                        microbatch=1, chunk=2)
    trainer = PipelineTrainer(pipe, _loss, optimizer=optax.sgd(0.01))

    # exact roundtrip before any step: shard -> pack -> unpack -> unshard
    exported = trainer.trained_params()
    flat_e, td_e = jax.tree.flatten(exported)
    flat_p, td_p = jax.tree.flatten(params)
    assert td_e == td_p
    for got, val in zip(flat_e, flat_p):
        np.testing.assert_allclose(np.asarray(got), np.asarray(val),
                                   rtol=1e-6, atol=1e-6)

    rng = np.random.default_rng(12)
    xs = rng.integers(0, 90, (2, 1, 16)).astype(np.float32)
    ys = rng.integers(0, 10, (2, 1))
    trainer.step(xs, ys)

    exported = trainer.trained_params()
    fresh = SpmdPipeline(stages, exported, mesh=pipeline_mesh(2),
                         microbatch=1, chunk=2)
    np.testing.assert_allclose(fresh.run(xs), pipe.run(xs),
                               rtol=2e-4, atol=2e-4)
