"""Framed transport tests: typed frames over a real socket, codec
negotiation, and an end-to-end remote-client -> pipeline-host loop
(the host/DCN edge of a deployment)."""

import socket
import threading

import jax
import numpy as np
import pytest

from defer_tpu import Defer, DeferConfig
from defer_tpu.models import resnet_tiny
from defer_tpu.transport import (K_BYTES, K_TENSOR, TensorClient,
                                 TensorServer, recv_frame, send_end,
                                 send_frame)


def test_frame_roundtrip_socketpair():
    a, b = socket.socketpair()
    x = np.arange(24, dtype=np.int16).reshape(2, 3, 4)
    send_frame(a, x)
    kind, y = recv_frame(b)
    assert kind == K_TENSOR and y.dtype == np.int16
    np.testing.assert_array_equal(x, y)

    send_frame(a, b"\x00\x01hello")
    kind, data = recv_frame(b)
    assert kind == K_BYTES and data == b"\x00\x01hello"

    # big frame exceeds the kernel socket buffer: send from a thread (a
    # single thread doing sendall-then-recv would deadlock on a socketpair)
    big = np.random.RandomState(0).randn(300_000).astype(np.float32)
    sender = threading.Thread(target=send_frame, args=(a, big),
                              kwargs={"codec": "bf8"})
    sender.start()
    _, got = recv_frame(b)
    sender.join(timeout=30)
    assert np.abs(big - got).max() <= np.abs(big).max() * 2**-7
    a.close(); b.close()


def test_end_frame():
    a, b = socket.socketpair()
    send_end(a)
    kind, v = recv_frame(b)
    from defer_tpu.transport import K_END
    assert kind == K_END and v is None
    a.close(); b.close()


def test_truncated_frame_raises():
    a, b = socket.socketpair()
    a.sendall(b"\x01\x03")  # header cut short
    a.close()
    with pytest.raises(ConnectionError):
        recv_frame(b)
    b.close()


def test_remote_edge_end_to_end():
    """A remote client streams inputs to a pipeline host over TCP with the
    lossy codec; the host runs the SPMD pipeline and streams back results —
    full capability parity with the reference's deployment
    (dispatcher <-> TCP <-> compute chain), with the chain inside one pod."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    defer = Defer(config=DeferConfig(microbatch=1, chunk=2))
    pipe = defer.build(g, params, num_stages=2)

    server = TensorServer()
    host, port = server.address

    def handler(x):
        return pipe.run(x[None])[0]

    t = threading.Thread(target=server.serve_once,
                         kwargs={"handler": handler, "codec": "raw"},
                         daemon=True)
    t.start()

    client = TensorClient(host, port)
    rng = np.random.RandomState(1)
    xs = [rng.randn(1, 32, 32, 3).astype(np.float32) for _ in range(3)]
    results = [client.infer(x, codec="bf12") for x in xs]
    client.close()
    t.join(timeout=30)
    server.close()

    fn = jax.jit(g.apply)
    for x, r in zip(xs, results):
        ref = np.asarray(fn(params, x), np.float32)
        np.testing.assert_allclose(r, ref, rtol=2e-3, atol=2e-3)
