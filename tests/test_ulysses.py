"""Ulysses (all_to_all) sequence parallelism == full attention == ring."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from defer_tpu.parallel.ring_attention import (full_attention,
                                               sequence_parallel_attention)
from defer_tpu.parallel.ulysses import sequence_parallel_attention_ulysses


def qkv(b=1, h=8, t=32, d=8, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(k, (b, h, t, d)) for k in ks)


def seq_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [2, 4, 8])
def test_ulysses_matches_full(n, causal):
    q, k, v = qkv()
    ref = full_attention(q, k, v, causal=causal)
    out = sequence_parallel_attention_ulysses(q, k, v, seq_mesh(n),
                                              causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_matches_ring():
    q, k, v = qkv(seed=3)
    mesh = seq_mesh(4)
    a = sequence_parallel_attention_ulysses(q, k, v, mesh, causal=True)
    b = sequence_parallel_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_head_divisibility():
    q, k, v = qkv(h=6)
    with pytest.raises(Exception, match="divisible"):
        sequence_parallel_attention_ulysses(q, k, v, seq_mesh(4))
