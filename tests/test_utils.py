"""Checkpoint save/restore + profiling breakdown + metrics/config units."""

import os

import jax
import numpy as np
import pytest

from defer_tpu import (DeferConfig, SpmdPipeline, StopwatchWindow,
                       load_params, partition, pipeline_mesh,
                       profile_pipeline, save_params)
from defer_tpu.models import resnet_tiny


def test_checkpoint_roundtrip(tmp_path):
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    path = os.path.join(tmp_path, "ckpt.npz")
    save_params(path, params)
    like = jax.eval_shape(lambda: g.init(jax.random.key(1)))
    restored = load_params(path, like)
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(restored)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_restore_then_deploy(tmp_path):
    """The deployment story: restore a checkpoint, place onto a pipeline."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    path = os.path.join(tmp_path, "ckpt.npz")
    save_params(path, params)
    restored = load_params(path, params)
    pipe = SpmdPipeline(partition(g, num_stages=2), restored,
                        mesh=pipeline_mesh(2), chunk=2)
    x = np.zeros((2, 1, 32, 32, 3), np.float32)
    ref = np.asarray(jax.jit(g.apply)(params, x[0]))
    np.testing.assert_allclose(pipe.run(x)[0], ref, rtol=2e-4, atol=2e-4)


def test_checkpoint_mismatch_fails_loudly(tmp_path):
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    path = os.path.join(tmp_path, "ckpt.npz")
    save_params(path, params)
    other = dict(params)
    other.pop(next(iter(other)))
    with pytest.raises(ValueError, match="mismatch"):
        load_params(path, other)


def test_profile_pipeline_breakdown():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    pipe = SpmdPipeline(partition(g, num_stages=4), params,
                        mesh=pipeline_mesh(4), chunk=2)
    prof = profile_pipeline(pipe, params, iters=2, warmup=1)
    assert prof["num_stages"] == 4
    assert len(prof["stage_latency_ms"]) == 4
    assert prof["stage_imbalance"] >= 1.0
    assert prof["pipeline_step_ms"] > 0
    assert prof["steady_state_throughput_per_s"] > 0


def test_stopwatch_window():
    w = StopwatchWindow(window_s=60)
    assert w.tick(5)
    assert w.count == 5
    assert w.rate > 0


def test_config_defaults():
    cfg = DeferConfig()
    assert cfg.mode == "spmd"
    assert cfg.microbatch == 1


def test_checkpoint_path_without_suffix(tmp_path):
    """save_params('x') writes x.npz; load_params('x') must find it."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    base = os.path.join(tmp_path, "ckpt")  # no .npz suffix
    save_params(base, params)
    restored = load_params(base, params)
    a = jax.tree_util.tree_leaves(params)[0]
    b = jax.tree_util.tree_leaves(restored)[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_metrics_clear_counters():
    """clear_counters zeroes the streaming counters but keeps geometry and
    stage latencies (harness warmup must not pollute a measured window)."""
    from defer_tpu.utils.metrics import PipelineMetrics

    m = PipelineMetrics(num_stages=4, microbatch=2, buffer_elems=64,
                        buffer_bytes_per_hop=256)
    m.inferences, m.steps, m.wall_s, m.chunk_calls = 10, 20, 1.5, 3
    m.stage_latency_s = [0.1, 0.2]
    m.clear_counters()
    assert (m.inferences, m.steps, m.wall_s, m.chunk_calls) == (0, 0, 0.0, 0)
    assert m.stage_latency_s == [0.1, 0.2]
    assert m.num_stages == 4 and m.buffer_elems == 64


def test_hop_utilization_property():
    """hop_utilization = per-stage out size / buf_elems, in stage order."""
    from defer_tpu import SpmdPipeline, partition, pipeline_mesh

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=2)
    util = pipe.hop_utilization
    assert len(util) == 4
    assert util == [s.out_spec.size / pipe.buf_elems for s in stages]
    assert all(0 < u <= 1 for u in util)


def test_flush_artifact_atomic_merge(tmp_path):
    """Timeout-safe artifact writer: atomic write, row merge across a
    re-run with a row filter, value recomputed over MERGED rows (the
    DECODE_r05 clobber scenario)."""
    import json
    from defer_tpu.utils.artifact import flush_artifact

    p = str(tmp_path / "a.json")
    # run 1: 2 rows, then times out
    flush_artifact(p, {"metric": "m", "value": 5.0,
                       "rows": {"a": {"tokens_per_s": 5.0},
                                "b": {"tokens_per_s": 3.0}}},
                   merge_key="rows")
    # run 2 (filtered re-run, merge_prior) measures only row c
    got = flush_artifact(p, {"metric": "m", "value": 2.0,
                             "rows": {"c": {"tokens_per_s": 2.0}}},
                         merge_key="rows", merge_prior=True)
    on_disk = json.loads(open(p).read())
    assert set(on_disk["rows"]) == {"a", "b", "c"}
    assert on_disk["value"] == 5.0  # max over merged, not just run 2
    assert got == on_disk
    # a FULL re-run (no merge_prior) replaces stale rows instead of
    # letting an obsolete fast row own the headline
    full = flush_artifact(p, {"metric": "m", "value": 0.0,
                              "rows": {"a": {"tokens_per_s": 4.0}}},
                          merge_key="rows")
    assert set(full["rows"]) == {"a"} and full["value"] == 4.0
    # row_filter restricts the headline (bench_spec: exclude baseline)
    f = flush_artifact(None, {"value": 0.0,
                              "rows": {"base": {"tokens_per_s": 9.0},
                                       "spec_x": {"tokens_per_s": 2.0}}},
                       merge_key="rows",
                       row_filter=lambda k: k.startswith("spec_"))
    assert f["value"] == 2.0
    # empty prior file must not crash the flush (the touch/stray-redirect
    # scenario)
    e = str(tmp_path / "empty.json")
    open(e, "w").close()
    flush_artifact(e, {"value": 0.0, "rows": {"a": {"tokens_per_s": 1.0}}},
                   merge_key="rows", merge_prior=True)
    assert json.loads(open(e).read())["value"] == 1.0
    # no .part file left behind
    assert not [f for f in tmp_path.iterdir() if f.suffix == ".part"]
    # no path -> no write, payload returned unchanged
    r = flush_artifact(None, {"x": 1})
    assert r == {"x": 1}


def test_ring_jit_kwargs_contract(monkeypatch):
    """Ring programs get the TPU defaults only on non-CPU meshes; env
    options merge over (and can disable) the defaults; CPU meshes never
    receive TPU-only flags implicitly."""
    import numpy as np
    import jax
    from defer_tpu.utils.xla_opts import (RING_DEFAULTS, compiler_options,
                                          jit_kwargs, ring_jit_kwargs)

    cpu_devices = np.array(jax.devices())  # conftest pins the cpu backend
    monkeypatch.delenv("DEFER_XLA_COMPILER_OPTS", raising=False)
    assert ring_jit_kwargs(cpu_devices) == {}
    assert jit_kwargs() == {}

    monkeypatch.setenv("DEFER_XLA_COMPILER_OPTS", "a=1, b=two")
    assert compiler_options() == {"a": "1", "b": "two"}
    assert ring_jit_kwargs(cpu_devices) == {
        "compiler_options": {"a": "1", "b": "two"}}

    class FakeTpu:
        platform = "tpu"

    tpu_devices = [FakeTpu()]
    opts = ring_jit_kwargs(tpu_devices)["compiler_options"]
    assert opts["a"] == "1"
    for k, v in RING_DEFAULTS.items():
        assert opts[k] == v
    # env overrides a default key-by-key
    key = next(iter(RING_DEFAULTS))
    monkeypatch.setenv("DEFER_XLA_COMPILER_OPTS", f"{key}=false")
    assert ring_jit_kwargs(tpu_devices)["compiler_options"][key] == "false"
