"""Checkpoint save/restore + profiling breakdown + metrics/config units."""

import os

import jax
import numpy as np
import pytest

from defer_tpu import (DeferConfig, SpmdPipeline, StopwatchWindow,
                       load_params, partition, pipeline_mesh,
                       profile_pipeline, save_params)
from defer_tpu.models import resnet_tiny


def test_checkpoint_roundtrip(tmp_path):
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    path = os.path.join(tmp_path, "ckpt.npz")
    save_params(path, params)
    like = jax.eval_shape(lambda: g.init(jax.random.key(1)))
    restored = load_params(path, like)
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(restored)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_restore_then_deploy(tmp_path):
    """The deployment story: restore a checkpoint, place onto a pipeline."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    path = os.path.join(tmp_path, "ckpt.npz")
    save_params(path, params)
    restored = load_params(path, params)
    pipe = SpmdPipeline(partition(g, num_stages=2), restored,
                        mesh=pipeline_mesh(2), chunk=2)
    x = np.zeros((2, 1, 32, 32, 3), np.float32)
    ref = np.asarray(jax.jit(g.apply)(params, x[0]))
    np.testing.assert_allclose(pipe.run(x)[0], ref, rtol=2e-4, atol=2e-4)


def test_checkpoint_mismatch_fails_loudly(tmp_path):
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    path = os.path.join(tmp_path, "ckpt.npz")
    save_params(path, params)
    other = dict(params)
    other.pop(next(iter(other)))
    with pytest.raises(ValueError, match="mismatch"):
        load_params(path, other)


def test_profile_pipeline_breakdown():
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    pipe = SpmdPipeline(partition(g, num_stages=4), params,
                        mesh=pipeline_mesh(4), chunk=2)
    prof = profile_pipeline(pipe, params, iters=2, warmup=1)
    assert prof["num_stages"] == 4
    assert len(prof["stage_latency_ms"]) == 4
    assert prof["stage_imbalance"] >= 1.0
    assert prof["pipeline_step_ms"] > 0
    assert prof["steady_state_throughput_per_s"] > 0


def test_stopwatch_window():
    w = StopwatchWindow(window_s=60)
    assert w.tick(5)
    assert w.count == 5
    assert w.rate > 0


def test_config_defaults():
    cfg = DeferConfig()
    assert cfg.mode == "spmd"
    assert cfg.microbatch == 1


def test_checkpoint_path_without_suffix(tmp_path):
    """save_params('x') writes x.npz; load_params('x') must find it."""
    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    base = os.path.join(tmp_path, "ckpt")  # no .npz suffix
    save_params(base, params)
    restored = load_params(base, params)
    a = jax.tree_util.tree_leaves(params)[0]
    b = jax.tree_util.tree_leaves(restored)[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_metrics_clear_counters():
    """clear_counters zeroes the streaming counters but keeps geometry and
    stage latencies (harness warmup must not pollute a measured window)."""
    from defer_tpu.utils.metrics import PipelineMetrics

    m = PipelineMetrics(num_stages=4, microbatch=2, buffer_elems=64,
                        buffer_bytes_per_hop=256)
    m.inferences, m.steps, m.wall_s, m.chunk_calls = 10, 20, 1.5, 3
    m.stage_latency_s = [0.1, 0.2]
    m.clear_counters()
    assert (m.inferences, m.steps, m.wall_s, m.chunk_calls) == (0, 0, 0.0, 0)
    assert m.stage_latency_s == [0.1, 0.2]
    assert m.num_stages == 4 and m.buffer_elems == 64


def test_hop_utilization_property():
    """hop_utilization = per-stage out size / buf_elems, in stage order."""
    from defer_tpu import SpmdPipeline, partition, pipeline_mesh

    g = resnet_tiny()
    params = g.init(jax.random.key(0))
    stages = partition(g, num_stages=4)
    pipe = SpmdPipeline(stages, params, mesh=pipeline_mesh(4),
                        microbatch=1, chunk=2)
    util = pipe.hop_utilization
    assert len(util) == 4
    assert util == [s.out_spec.size / pipe.buf_elems for s in stages]
    assert all(0 < u <= 1 for u in util)
